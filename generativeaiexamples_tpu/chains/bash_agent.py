"""bash_agent — allowlisted bash computer-use agent loop.

Behavioral parity with the reference's from-scratch bash agent
(ref: nemotron/LLM/bash_computer_use_agent/{main_from_scratch,bash,config}.py —
an LLM drives an `exec_bash_command` tool; bash.py:exec_bash_command blocks
`` ` `` / ``$`` injection patterns, splits compound commands and checks
every part against an allowlist, tracks the working directory; the main
loop confirms each execution with the user and feeds tool results back
until the model answers without a tool call).

The reference's OpenAI tool-calling wire format is replaced by a JSON-in-
text protocol (the in-proc LLM is a plain chat stream): the model either
emits ``{"tool": "exec_bash_command", "cmd": "..."}`` or a final answer.
Safety posture is strictly tighter than the reference: same injection
guards and allowlist, plus a **deny-by-default confirm callback** — headless
runs execute nothing unless the embedder explicitly supplies a policy —
and output size/time caps on every command.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shlex
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# ref config.py: read-only inspection commands; anything mutating requires
# the operator to extend the allowlist deliberately
DEFAULT_ALLOWED = ("ls", "pwd", "cat", "head", "tail", "wc", "grep", "find",
                   "echo", "date", "whoami", "du", "df", "file", "stat",
                   "uname", "cd")

# Flags that turn an allowlisted command into a write/exec primitive —
# `find -delete` passes every other guard yet wipes the tree; `-exec`
# escapes the allowlist entirely. Checked across ALL tokens of a command.
DENIED_TOKENS = frozenset({
    "-delete", "-exec", "-execdir", "-ok", "-okdir",
    "-fprint", "-fprint0", "-fprintf", "-fls",     # find's file writers
})

SYSTEM_PROMPT = """\
You are a careful computer-use assistant operating a bash shell.
To run a command, reply with ONLY this JSON (no other text):
{"tool": "exec_bash_command", "cmd": "<command>"}
You will receive the result as a tool message. When you have enough
information, reply with a plain-text answer instead of JSON.
Rules: one command per turn; only simple commands (no backticks, no $());
prefer read-only inspection."""


@dataclass
class BashTool:
    """Sandboxed command executor (ref bash.py Bash class)."""

    allowed_commands: Sequence[str] = DEFAULT_ALLOWED
    root_dir: str = "."
    timeout_s: float = 10.0
    max_output: int = 4096
    cwd: str = field(init=False)

    def __post_init__(self) -> None:
        self.cwd = os.path.abspath(self.root_dir)

    # -- validation (ref bash.py:exec_bash_command) -----------------------

    @staticmethod
    def _split_commands(cmd: str) -> List[str]:
        """Leading command word of every segment of a compound command."""
        parts = re.split(r"\|\||&&|\||;|&|\n", cmd)
        words = []
        for part in parts:
            try:
                tokens = shlex.split(part.strip())
            except ValueError:
                return ["<unparseable>"]
            if tokens:
                words.append(tokens[0])
        return words

    def exec_bash_command(self, cmd: str) -> Dict[str, str]:
        if not cmd or not cmd.strip():
            return {"error": "No command was provided."}
        # injection guards (ref bash.py: backticks and $ block substitution
        # and variables alike); also block redirection (`>` would make the
        # read-only `echo` a write primitive) and `&` outright — a lone
        # ampersand backgrounds a second command that the compound-split
        # below would never see
        if re.search(r"[`$<>&]", cmd):
            return {"error": "Command injection/redirection/background "
                             "patterns are not allowed."}
        for word in self._split_commands(cmd):
            if word not in self.allowed_commands:
                return {"error": f"Command {word!r} is not in the allowlist."}
        try:
            all_tokens = shlex.split(cmd)
        except ValueError:
            return {"error": "Unparseable command."}
        denied = DENIED_TOKENS.intersection(all_tokens)
        if denied:
            return {"error": f"Flag {sorted(denied)[0]!r} is not allowed "
                             "(write/exec primitive)."}
        # `cd` updates tracked cwd instead of spawning a shell
        tokens = all_tokens
        if tokens and tokens[0] == "cd":
            target = os.path.abspath(os.path.join(
                self.cwd, tokens[1] if len(tokens) > 1 else "."))
            if not os.path.isdir(target):
                return {"error": f"No such directory: {target}"}
            self.cwd = target
            return {"stdout": "", "stderr": "", "cwd": self.cwd}
        return self._run(cmd)

    def _run(self, cmd: str) -> Dict[str, str]:
        try:
            proc = subprocess.run(
                cmd, shell=True, cwd=self.cwd, capture_output=True,
                text=True, timeout=self.timeout_s)
        except subprocess.TimeoutExpired:
            return {"error": f"Command timed out after {self.timeout_s}s."}
        return {"stdout": proc.stdout[-self.max_output:],
                "stderr": proc.stderr[-self.max_output:],
                "returncode": str(proc.returncode), "cwd": self.cwd}


def parse_tool_call(text: str) -> Optional[str]:
    """Extract a {"tool": "exec_bash_command", "cmd": ...} call; None means
    the reply is a final answer."""
    from generativeaiexamples_tpu.chains.query_decomposition import (
        extract_json)

    obj = extract_json(text)
    if (isinstance(obj, dict) and obj.get("tool") == "exec_bash_command"
            and isinstance(obj.get("cmd"), str)):
        return obj["cmd"]
    return None


class BashAgent:
    """The agent loop (ref main_from_scratch.py): user goal → model → tool
    call → confirm → execute → tool result → ... → final answer.

    ``confirm(cmd) -> bool`` gates every execution; the DEFAULT DENIES
    (the reference prompts interactively — headless callers must opt in
    with an explicit policy, e.g. ``confirm=lambda cmd: True`` for the
    allowlisted read-only set).
    """

    def __init__(self, llm, tool: Optional[BashTool] = None,
                 confirm: Optional[Callable[[str], bool]] = None,
                 max_turns: int = 8) -> None:
        self.llm = llm
        self.tool = tool or BashTool()
        self.confirm = confirm or (lambda cmd: False)
        self.max_turns = max_turns

    def run(self, goal: str) -> Tuple[str, List[Dict[str, str]]]:
        """Drive the loop; returns (final_answer, transcript). The
        transcript records every tool call and result for auditing."""
        messages: List[Dict[str, str]] = [
            {"role": "system", "content": SYSTEM_PROMPT},
            {"role": "user",
             "content": f"{goal}\nCurrent working directory: "
                        f"`{self.tool.cwd}`"},
        ]
        transcript: List[Dict[str, str]] = []
        for _ in range(self.max_turns):
            reply = "".join(self.llm.chat(messages, max_tokens=256,
                                          temperature=0.0)).strip()
            cmd = parse_tool_call(reply)
            if cmd is None:
                return reply, transcript
            if self.confirm(cmd):
                result = self.tool.exec_bash_command(cmd)
            else:
                result = {"error": "Execution declined by policy."}
            transcript.append({"cmd": cmd, **result})
            messages.append({"role": "assistant", "content": reply})
            messages.append({
                "role": "user",
                "content": f"Tool result: {json.dumps(result)}\n"
                           f"Current working directory: `{self.tool.cwd}`"})
        return ("I hit the step limit before finishing; partial results "
                "are in the transcript."), transcript
