"""Time-series predictors for the asset-lifecycle agent: RUL + anomalies.

In-tree analogue of the reference's MOMENT-based predictor tools
(ref: industries/asset_lifecycle_management_agent/src/
asset_lifecycle_management_agent/predictors/moment_predict_rul_tool.py —
per-unit sensor history → forecast degradation over a horizon → first
failure-threshold crossing → RUL, capped; and predict_rul_tool.py's
statistical fallback). TPU-first redesign: instead of a 385M-parameter
foundation forecaster in a torch container, a jitted trend+AR(1)
forecaster — closed-form least squares, vmapped over sensor channels —
covers the monotone-degradation regime the RUL computation actually
consumes, runs in microseconds on the serving chip, and stays fully
deterministic for agent evaluation.

Surfaces: pure functions (`forecast`, `predict_rul`, `detect_anomalies`)
plus `Tool` wrappers (chains/tool_agent.py) so the asset-lifecycle agent
calls them the way the reference's NAT agent calls its predictor tools.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.chains.tool_agent import Tool


@jax.jit
def _fit_trend_ar(y: jnp.ndarray):
    """Per-channel linear trend + AR(1) residual fit. y: (T, F) float32 →
    (slope (F,), intercept (F,), phi (F,), last_resid (F,))."""
    T, F = y.shape
    t = jnp.arange(T, dtype=jnp.float32)
    tm = t.mean()
    ym = y.mean(axis=0)
    tc = t - tm
    denom = jnp.maximum((tc ** 2).sum(), 1e-9)
    slope = (tc[:, None] * (y - ym)).sum(axis=0) / denom        # (F,)
    intercept = ym - slope * tm
    resid = y - (intercept + slope * t[:, None])
    r0 = resid[:-1]
    r1 = resid[1:]
    phi = ((r0 * r1).sum(axis=0)
           / jnp.maximum((r0 ** 2).sum(axis=0), 1e-9))
    phi = jnp.clip(phi, -0.99, 0.99)
    return slope, intercept, phi, resid[-1]


from functools import partial


@partial(jax.jit, static_argnums=(5,))
def _extrapolate(slope, intercept, phi, last_resid, t0: jnp.ndarray,
                 horizon: int):
    """Forecast `horizon` steps past t0: trend + geometrically decaying
    AR(1) residual. → (horizon, F)."""
    steps = jnp.arange(1, horizon + 1, dtype=jnp.float32)[:, None]
    trend = intercept[None] + slope[None] * (t0 + steps)
    return trend + last_resid[None] * (phi[None] ** steps)


def forecast(series: np.ndarray, horizon: int) -> np.ndarray:
    """series: (T, F) sensor history → (horizon, F) forecast."""
    y = jnp.asarray(np.asarray(series, np.float32))
    if y.ndim == 1:
        y = y[:, None]
    slope, intercept, phi, last = _fit_trend_ar(y)
    out = _extrapolate(slope, intercept, phi, last,
                       jnp.float32(y.shape[0] - 1), int(horizon))
    return np.asarray(out)


def predict_rul(series: np.ndarray, failure_threshold: float,
                horizon: int = 96, max_rul_cycles: int = 500,
                min_history: int = 8) -> Dict[str, Any]:
    """Remaining useful life from a degradation (health-index) series.

    Mirrors the reference's calculation (moment_predict_rul_tool.py
    calculate_rul_from_degradation): forecast the health index, find the
    first step crossing ``failure_threshold`` (degradation INCREASES
    toward failure), cap at ``max_rul_cycles``; if the forecast never
    crosses, extrapolate the trend rate; with a flat/improving trend,
    report the conservative 0.8 × cap the reference uses.
    """
    arr = np.asarray(series, np.float32).reshape(len(series), -1)
    if arr.shape[0] < min_history:
        return {"status": "insufficient_data",
                "rul": max_rul_cycles * 0.5}
    health = arr.mean(axis=1)                         # scalar health index
    fc = forecast(health, horizon)[:, 0]
    crossing = np.nonzero(fc >= failure_threshold)[0]
    if crossing.size:
        rul = float(crossing[0] + 1)
        status = "forecast_crossing"
    else:
        slope = float(fc[-1] - fc[0]) / max(horizon - 1, 1)
        if slope > 1e-9:
            rul = horizon + (failure_threshold - float(fc[-1])) / slope
            status = "trend_extrapolation"
        else:
            rul = max_rul_cycles * 0.8                # conservative cap
            status = "no_degradation_trend"
    rul = float(max(1.0, min(rul, max_rul_cycles)))
    return {"status": status, "rul": rul,
            "current_health": float(health[-1]),
            "failure_threshold": float(failure_threshold)}


def detect_anomalies(series: np.ndarray, z_threshold: float = 4.0
                     ) -> Dict[str, Any]:
    """Robust anomaly scan: AR(1) INNOVATIONS (whitened residuals — a
    smooth seasonal signal has small innovations, so a spike cannot hide
    inside its own variance) scored by modified z-score (median/MAD — one
    outlier cannot mask another). Returns anomalous indices and scores."""
    arr = np.asarray(series, np.float32).reshape(len(series), -1)
    y = jnp.asarray(arr)
    slope, intercept, phi, _ = _fit_trend_ar(y)
    t = jnp.arange(arr.shape[0], dtype=jnp.float32)[:, None]
    resid = np.asarray(y - (intercept[None] + slope[None] * t))
    innov = resid[1:] - np.asarray(phi)[None] * resid[:-1]
    med = np.median(innov, axis=0)
    mad = np.median(np.abs(innov - med), axis=0)
    z = 0.6745 * (innov - med) / np.maximum(mad, 1e-9)
    full = np.abs(z).max(axis=1)
    # a spike perturbs the innovation at its index AND the next one;
    # attribute each anomalous innovation to the point that caused it
    score = np.zeros(arr.shape[0], np.float32)
    score[1:] = full
    idx = np.nonzero(score > z_threshold)[0]
    # collapse the spike's trailing echo onto the spike itself
    idx = np.asarray([i for j, i in enumerate(idx)
                      if j == 0 or i != idx[j - 1] + 1])
    return {"anomalies": [{"index": int(i), "score": round(float(score[i]), 2)}
                          for i in idx],
            "n_points": int(arr.shape[0])}


# -------------------------------------------------------------- agent tools

def _parse_series(blob: str) -> np.ndarray:
    data = json.loads(blob)
    if isinstance(data, dict):
        data = data.get("series", data.get("values"))
    return np.asarray(data, np.float32)


def predictor_tools(max_rul_cycles: int = 500,
                    horizon: int = 96) -> List[Tool]:
    """The asset-lifecycle agent's predictor tools (ref: the NAT agent's
    moment_predict_rul_tool / anomaly detection tool registrations)."""

    def rul_fn(series: str, failure_threshold: float) -> str:
        out = predict_rul(_parse_series(series), failure_threshold,
                          horizon=horizon, max_rul_cycles=max_rul_cycles)
        return json.dumps(out)

    def anom_fn(series: str, z_threshold: float = 4.0) -> str:
        return json.dumps(detect_anomalies(_parse_series(series),
                                           z_threshold))

    series_schema = {"type": "string",
                     "description": "JSON array of sensor readings "
                                    "(oldest first), or {\"series\": [...]}"}
    return [
        Tool(name="predict_rul",
             description="Predict remaining useful life (cycles) of an "
                         "asset from its degradation/health-index history.",
             parameters={"type": "object", "properties": {
                 "series": series_schema,
                 "failure_threshold": {
                     "type": "number",
                     "description": "health-index value at which the "
                                    "asset is considered failed"}},
                 "required": ["series", "failure_threshold"]},
             fn=rul_fn),
        Tool(name="detect_anomalies",
             description="Find anomalous readings in a sensor series "
                         "(robust z-score on detrended residuals).",
             parameters={"type": "object", "properties": {
                 "series": series_schema,
                 "z_threshold": {"type": "number", "default": 4.0}},
                 "required": ["series"]},
             fn=anom_fn),
    ]
