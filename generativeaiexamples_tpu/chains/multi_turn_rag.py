"""multi_turn_rag — two-store retrieval with the 40→4 rerank funnel.

Behavioral parity with the reference example
(ref: RAG/examples/advanced_rag/multi_turn_rag/chains.py): keeps a document
store and a conversation-memory store; each turn retrieves from BOTH with a
wide net (top_k=40 when a ranker is configured, chains.py:146-147), narrows
each pool to `retriever.top_k` with the cross-encoder
(ranker.compress_documents, chains.py:173-190), renders the multi-turn
template with {history} and {context}, streams, then writes the exchange
back into the conversation store (save_memory_and_get_output,
chains.py:63-68).

TPU design: both rerank passes are single bucketed cross-encoder batches
(one jitted forward each — see encoders/reranker.py), so the funnel costs
~2 forwards instead of 80 HTTP calls — and they ISSUE CONCURRENTLY, so the
pair-granular micro-batcher (encoders/microbatch.py) can merge them into
one. When the request carries chat history, the follow-up is first
condensed into a standalone query (prompts.query_rewriter_prompt) with the
raw-query retrieval speculatively overlapped behind that LLM call
(chains/lookahead.py, TeleRAG) — docs/rag_pipeline.md has the full
dataplane picture.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterator, List, Sequence

from generativeaiexamples_tpu.chains.basic_rag import _sampling, trim_context
from generativeaiexamples_tpu.server import guardrails
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.chains.loaders import load_document
from generativeaiexamples_tpu.chains.lookahead import (
    LookaheadRetrieval, submit_concurrently)
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.observability.otel import stage_span
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

from generativeaiexamples_tpu.chains import NO_CONTEXT_MSG

DOCS = "multi_turn_docs"
CONV = "multi_turn_conv"
WIDE_TOP_K = 40  # ref chains.py:146 — "Get 40 results ... compress them to 4"


@register_example("multi_turn_rag")
class MultiTurnRAG(BaseExample):
    def __init__(self, context: ChainContext = None) -> None:
        self.ctx = context or get_context()

    # ------------------------------------------------------------ ingestion

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        if not filename.lower().endswith((".txt", ".pdf", ".md")):
            raise ValueError(
                f"{filename} is not a valid Text, PDF or Markdown file")
        text = load_document(filepath)
        if not text.strip():
            raise ValueError(f"no text extracted from {filename}")
        chunks = self.ctx.splitter().split(text)
        docs = [Document(content=c, metadata={"source": filename})
                for c in chunks]
        embeddings = self.ctx.embedder.embed_documents([d.content for d in docs])
        self.ctx.store(DOCS).add(docs, embeddings)
        logger.info("ingested %s: %d chunks", filename, len(docs))

    # -------------------------------------------------------------- memory

    def _save_memory(self, query: str, output: str) -> None:
        """Write the turn into the conversation store
        (ref save_memory_and_get_output, chains.py:63-68)."""
        texts = [f"User previously responded with {query}",
                 f"Agent previously responded with {output}"]
        docs = [Document(content=t, metadata={"source": "conversation"})
                for t in texts]
        embeddings = self.ctx.embedder.embed_documents(texts)
        self.ctx.store(CONV).add(docs, embeddings)

    def _retrieve_pool(self, collection: str, qvec, wide: bool) -> List[str]:
        rcfg = self.ctx.config.retriever
        top_k = WIDE_TOP_K if (wide and self.ctx.reranker) else rcfg.top_k
        hits = self.ctx.store(collection).search(
            qvec, top_k=top_k, score_threshold=rcfg.score_threshold)
        return [d.content for d, _ in hits]

    def _funnel(self, query: str, pool: List[str]) -> List[str]:
        """40→top_k cross-encoder narrowing (ref chains.py:173-190)."""
        if not pool or not self.ctx.reranker:
            return pool
        top_n = self.ctx.config.retriever.top_k
        ranked = self.ctx.reranker.rerank(query, pool, top_n=top_n)
        return [pool[i] for i, _ in ranked]

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        # ref chains.py:96-98: chat history handled via the conversation
        # store, not the raw message list
        messages = [{"role": "system",
                     "content": self.ctx.prompts["chat_template"]},
                    {"role": "user", "content": query}]
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    def _condense(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **settings: Any) -> str:
        """Rewrite a follow-up question into a standalone retrieval query
        using the turn history (the condense step of the reference's
        conversational examples; prompts.query_rewriter_prompt)."""
        s = _sampling(settings)
        s.update(max_tokens=96, temperature=0.0)
        history_txt = "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in chat_history)
        out = "".join(self.ctx.llm.chat(
            [{"role": "system",
              "content": self.ctx.prompts["query_rewriter_prompt"]},
             {"role": "user",
              "content": f"History:\n{history_txt}\n\n"
                         f"Follow-up question: {query}"}], **s)).strip()
        return out or query

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        rcfg = self.ctx.config.retriever

        def retrieve_pools(q: str, qvec=None):
            if qvec is None:
                qvec = self.ctx.embedder.embed_queries([q])[0]
            return qvec, (self._retrieve_pool(DOCS, qvec, wide=True),
                          self._retrieve_pool(CONV, qvec, wide=True))

        search_query = query
        if chat_history:
            # Lookahead retrieval (TeleRAG, chains/lookahead.py): the
            # condense LLM call and the raw-query retrieval run CONCURRENTLY;
            # reconcile reuses the speculative pools when the rewrite stays
            # close in embedding space and re-retrieves otherwise
            look = LookaheadRetrieval(retrieve_pools).start(query)
            with stage_span("condense"):
                search_query = self._condense(query, chat_history,
                                              **llm_settings)
            with stage_span("retrieve"):
                _, (context_pool, history_pool) = look.reconcile(
                    search_query,
                    embed=lambda q: self.ctx.embedder.embed_queries([q])[0])
        else:
            with stage_span("retrieve"):
                _, (context_pool, history_pool) = retrieve_pools(query)

        # both funnels issue together: the reranker micro-batcher coalesces
        # their (query, passage) pairs into a shared cross-encoder dispatch
        with stage_span("rerank"):
            context, history = submit_concurrently(
                lambda: self._funnel(search_query, context_pool),
                lambda: self._funnel(search_query, history_pool))

        if not context and not history:
            yield NO_CONTEXT_MSG  # ref chains.py:198-203
            return

        tok = self.ctx.embedder.tokenizer
        budget = rcfg.max_context_tokens
        # history gets at most half the budget; context gets what's left, so
        # the combined prompt never exceeds max_context_tokens
        history_text = trim_context(history, tok, budget // 2)
        context_budget = budget - len(tok.encode(history_text))
        context_text = trim_context(context, tok, context_budget)
        guardrails.record_context(context_text)
        system = self.ctx.prompts["multi_turn_rag_template"].format(
            history=history_text or "(none)",
            context=context_text or "(none)")
        messages = [{"role": "system", "content": system},
                    {"role": "user", "content": query}]

        response = ""
        with stage_span("generate"):
            for chunk in self.ctx.llm.chat(messages, **_sampling(llm_settings)):
                response += chunk
                yield chunk
        self._save_memory(query, response)

    # ------------------------------------------------------------ documents

    def document_search(self, query: str, num_docs: int = 4) -> List[Dict[str, Any]]:
        qvec = self.ctx.embedder.embed_queries([query])[0]
        hits = self.ctx.store(DOCS).search(
            qvec, top_k=num_docs,
            score_threshold=self.ctx.config.retriever.score_threshold)
        return [{"source": str(d.metadata.get("source", "")),
                 "content": d.content, "score": score}
                for d, score in hits]

    def get_documents(self) -> List[str]:
        return self.ctx.store(DOCS).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> bool:
        return self.ctx.store(DOCS).delete_by_source(filenames) > 0
