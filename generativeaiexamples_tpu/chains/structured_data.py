"""structured_data_rag — CSV Q&A via an LLM→pandas code-generation agent.

Behavioral parity with the reference example (ref: RAG/examples/
advanced_rag/structured_data_rag/chains.py): ingest validates CSVs and
requires matching columns across files (compare_csv_columns, chains.py:64-76;
ingested-file list chains.py:108-133); rag_chain concatenates the CSVs,
builds a column+sample-rows description (csv_utils.extract_df_desc), has the
LLM write pandas code with retries (PandasAI_Agent w/ max_retries=6,
chains.py:176-179), and paraphrases the resulting data point through the
response template (chains.py:206-215).

Differences by design: instead of PandasAI's exec-based code runner, the
generated code is validated against an AST allowlist — no imports, no
underscore attributes, `pd.<attr>` limited to a constructor/transform
allowlist (blocking the `pd.io`/`pd.read_*`/`pd.eval` escape hatches), and
IO/exec method names (`to_csv`, `query`, `eval`, `pipe`, …) rejected on any
object — then executed with a minimal namespace. This is the sandboxing the
reference delegates to the PandasAI library.
"""

from __future__ import annotations

import ast
import logging
import os
from typing import Any, Dict, Iterator, List, Sequence

from generativeaiexamples_tpu.chains.basic_rag import _sampling
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

from generativeaiexamples_tpu.chains import NO_CONTEXT_MSG

MAX_RETRIES = 6  # ref chains.py:178 — config_data_retrieval max_retries

_ALLOWED_NODES = (
    ast.Module, ast.Expr, ast.Assign, ast.AugAssign, ast.Name, ast.Load,
    ast.Store, ast.Constant, ast.Tuple, ast.List, ast.Dict, ast.Set,
    ast.Attribute, ast.Subscript, ast.Slice, ast.Index if hasattr(ast, "Index") else ast.Slice,
    ast.Call, ast.keyword, ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.USub, ast.UAdd, ast.Not, ast.Invert, ast.And, ast.Or, ast.Eq,
    ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE, ast.In, ast.NotIn,
    ast.BitAnd, ast.BitOr, ast.BitXor, ast.IfExp, ast.ListComp, ast.DictComp,
    ast.SetComp, ast.GeneratorExp, ast.comprehension, ast.Lambda,
    ast.arguments, ast.arg, ast.Starred, ast.JoinedStr, ast.FormattedValue,
)

# pd.<attr> the generated code may use: constructors and pure transforms
# only — nothing that reaches IO, eval, or submodules (pd.io.common exposes
# `os`; pd.read_* / pd.eval are filesystem/exec escapes).
_PD_ALLOWED = {
    "to_datetime", "to_numeric", "to_timedelta", "concat", "merge",
    "DataFrame", "Series", "Timestamp", "Timedelta", "NaT", "NA",
    "Grouper", "NamedAgg", "Categorical", "Index", "MultiIndex",
    "pivot_table", "crosstab", "cut", "qcut", "date_range", "unique",
    "isna", "notna", "isnull", "notnull", "get_dummies", "melt",
    "wide_to_long", "factorize", "array", "options",
}

# method/attribute names disallowed on ANY object: dataframe IO writers,
# string-eval surfaces, and module traversal hatches.
_DENIED_ATTRS = {
    "to_csv", "to_json", "to_pickle", "to_excel", "to_parquet", "to_sql",
    "to_hdf", "to_feather", "to_clipboard", "to_html", "to_latex",
    "to_xml", "to_stata", "to_orc", "to_markdown", "to_records",
    "read_csv", "read_json", "read_pickle", "read_excel", "read_parquet",
    "read_sql", "read_hdf", "read_feather", "read_html", "read_xml",
    "read_table", "read_fwf", "read_clipboard", "read_orc", "read_stata",
    "read_sas", "read_spss", "read_gbq",
    "eval", "query", "pipe", "io", "os", "sys", "builtins", "compat",
    "api", "core", "util", "testing", "errors", "tseries", "attrs",
    "style", "plot", "plotting", "globals", "getattr", "setattr",
}

_SAFE_BUILTINS = {
    "len": len, "min": min, "max": max, "sum": sum, "abs": abs,
    "round": round, "sorted": sorted, "str": str, "int": int, "float": float,
    "bool": bool, "list": list, "dict": dict, "tuple": tuple, "set": set,
    "range": range, "zip": zip, "enumerate": enumerate, "any": any,
    "all": all, "map": map, "filter": filter, "reversed": reversed,
}


def validate_code(code: str) -> ast.Module:
    """Parse + allowlist-check LLM-generated pandas code."""
    tree = ast.parse(code)
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ValueError(
                f"disallowed syntax: {type(node).__name__}")
        if isinstance(node, ast.Attribute):
            if node.attr.startswith("_"):
                raise ValueError(f"disallowed attribute: {node.attr}")
            if node.attr in _DENIED_ATTRS:
                raise ValueError(f"disallowed attribute: {node.attr}")
            if (isinstance(node.value, ast.Name) and node.value.id == "pd"
                    and node.attr not in _PD_ALLOWED):
                raise ValueError(f"disallowed pandas attribute: {node.attr}")
        if isinstance(node, ast.Name) and node.id.startswith("__"):
            raise ValueError(f"disallowed name: {node.id}")
    return tree


def run_pandas_code(code: str, df) -> Any:
    """Execute validated code with only {df, pd, builtins-allowlist};
    the answer is `result` (or the last expression's value)."""
    import pandas as pd

    tree = validate_code(code)
    # make a bare trailing expression become `result`
    if tree.body and isinstance(tree.body[-1], ast.Expr):
        tree.body[-1] = ast.Assign(
            targets=[ast.Name(id="result", ctx=ast.Store())],
            value=tree.body[-1].value)
        ast.fix_missing_locations(tree)
    namespace: Dict[str, Any] = {"df": df, "dfs": [df], "pd": pd,
                                 "__builtins__": _SAFE_BUILTINS}
    exec(compile(tree, "<llm-pandas>", "exec"), namespace)  # noqa: S102
    return namespace.get("result")


def extract_df_desc(df) -> str:
    """Column names + up to 3 sample rows (ref csv_utils.extract_df_desc,
    csv_utils.py:26-40; head() instead of sample() for determinism)."""
    column_names = ", ".join(df.columns)
    rows_str = df.head(3).to_string(header=False, index=False)
    return column_names + "\n" + rows_str


def strip_code_fences(text: str) -> str:
    text = text.strip()
    if text.startswith("```"):
        lines = text.split("\n")
        lines = lines[1:]
        if lines and lines[-1].strip().startswith("```"):
            lines = lines[:-1]
        text = "\n".join(lines)
    return text.strip()


def is_result_valid(result: Any) -> bool:
    """ref csv_utils.is_result_valid, csv_utils.py:115-119."""
    import pandas as pd

    if isinstance(result, pd.DataFrame):
        return not result.empty
    if isinstance(result, pd.Series):
        return len(result) > 0
    return result is not None and bool(str(result))


@register_example("structured_data_rag")
class StructuredDataRAG(BaseExample):
    """CSV chatbot (ref CSVChatbot, chains.py:60)."""

    def __init__(self, context: ChainContext = None,
                 state_dir: str = "") -> None:
        self.ctx = context or get_context()
        self.state_dir = state_dir or os.environ.get(
            "APP_STATE_DIR", "/tmp/generativeaiexamples_tpu")
        os.makedirs(self.state_dir, exist_ok=True)
        self.files_list = os.path.join(self.state_dir,
                                       "ingested_csv_files.txt")

    # ------------------------------------------------------------ ingestion

    def _csv_paths(self) -> List[str]:
        if not os.path.exists(self.files_list):
            return []
        with open(self.files_list, "r", encoding="utf-8") as fh:
            return [l.strip() for l in fh.read().splitlines() if l.strip()]

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        import pandas as pd

        if not filename.lower().endswith(".csv"):
            raise ValueError(f"{filename} is not a valid CSV file")
        paths = self._csv_paths()
        if paths:  # column compatibility (ref compare_csv_columns)
            ref_df = pd.read_csv(paths[0], nrows=1)
            new_df = pd.read_csv(filepath, nrows=1)
            if not new_df.columns.equals(ref_df.columns):
                raise ValueError(
                    f"Columns of the file {filepath} do not match the "
                    f"reference columns of {paths[0]} file.")
        else:
            pd.read_csv(filepath, nrows=1)  # must parse
        if filepath not in paths:  # re-upload replaces in place, no dup rows
            with open(self.files_list, "a", encoding="utf-8") as fh:
                fh.write(filepath + "\n")
        logger.info("Document %s ingested successfully", filename)

    def _load_df(self):
        """Read + concatenate all ingested CSVs
        (ref read_and_concatenate_csv, chains.py:78-106)."""
        import pandas as pd

        paths = self._csv_paths()
        if not paths:
            return None
        frames = [pd.read_csv(p) for p in paths]
        ref_cols = frames[0].columns
        for path, frame in zip(paths[1:], frames[1:]):
            if not frame.columns.equals(ref_cols):
                raise ValueError(
                    f"Columns of the file {path} do not match the reference "
                    f"columns of {paths[0]} file.")
        return pd.concat(frames, ignore_index=True).fillna(0)

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        messages = [{"role": "system",
                     "content": self.ctx.prompts["chat_template"]},
                    {"role": "user", "content": query}]
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    def _generate_result(self, df, query: str, **settings: Any) -> Any:
        """LLM writes pandas code; retry with error feedback
        (PandasAI-agent equivalent, ref chains.py:176-200)."""
        csv_name = os.environ.get("CSV_NAME", "")
        description, instructions = csv_name or "a CSV table", "- none"
        for p in self.ctx.prompts.get("csv_prompts", []) or []:
            if isinstance(p, dict) and p.get("name") == csv_name:
                description = p.get("description", description)
                instructions = p.get("instructions", instructions)
        system = self.ctx.prompts["csv_data_retrieval_template"].format(
            description=description, instructions=instructions,
            data_frame=extract_df_desc(df))
        error = ""
        s = _sampling(settings)
        s["temperature"] = 0.2  # ref: PandasAI_NVIDIA(temperature=0.2)
        s["max_tokens"] = min(s["max_tokens"], 384)
        for attempt in range(MAX_RETRIES):
            user = query if not error else (
                f"{query}\n\nYour previous code failed with: {error}\n"
                f"Write corrected code.")
            raw = "".join(self.ctx.llm.chat(
                [{"role": "system", "content": system},
                 {"role": "user", "content": user}], **s))
            code = strip_code_fences(raw)
            try:
                result = run_pandas_code(code, df)
                if is_result_valid(result):
                    return result
                error = "result was empty or None"
            except Exception as exc:
                error = str(exc)
                logger.info("pandas code attempt %d failed: %s",
                            attempt + 1, error)
        return None

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        df = self._load_df()
        if df is None:
            yield "No CSV file ingested"  # ref chains.py:166
            return
        result = self._generate_result(df, query, **llm_settings)
        if not is_result_valid(result):
            yield NO_CONTEXT_MSG
            return
        logger.info("Result data point: %s", result)
        prompt = self.ctx.prompts["csv_response_template"].format(
            query=query, data=str(result))
        yield from self.ctx.llm.chat(
            [{"role": "user", "content": prompt}], **_sampling(llm_settings))

    # ------------------------------------------------------------ documents

    def get_documents(self) -> List[str]:
        return [os.path.basename(p) for p in self._csv_paths()]

    def delete_documents(self, filenames: Sequence[str]) -> bool:
        names = set(filenames)
        paths = self._csv_paths()
        keep = [p for p in paths if os.path.basename(p) not in names]
        if len(keep) == len(paths):
            return False
        with open(self.files_list, "w", encoding="utf-8") as fh:
            fh.write("".join(p + "\n" for p in keep))
        return True
