"""Multimodal document parsers: PDF / PPTX / PNG → text + image elements.

Behavioral parity with the reference's custom parsers (ref: RAG/examples/
advanced_rag/multimodal_rag/vectorstore/custom_pdf_parser.py:312
get_pdf_documents — text blocks + embedded images + tables;
custom_powerpoint_parser.py — slide text + media; custom_img_parser.py —
standalone images), without the pymupdf/python-pptx/tesseract stack: PDFs
are parsed with the in-tree stream walker (chains/loaders.py) plus an
object-level scan for embedded images; PPTX is unzipped and the slide XML
read directly; images are decoded with Pillow.

Each parser returns a list of `Element`s; image elements carry the decoded
image so the chain can caption them (VLM seam in chains/multimodal.py).
"""

from __future__ import annotations

import io
import logging
import os
import re
import xml.etree.ElementTree as ET
import zipfile
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass
class Element:
    """One extracted unit: a text passage or an image."""
    kind: str                      # "text" | "image"
    text: str = ""                 # text content, or caption once described
    image_bytes: bytes = b""       # encoded image (png/jpeg) for kind=image
    metadata: Dict[str, str] = field(default_factory=dict)


# ------------------------------------------------------------------- PDF

_OBJ_RE = re.compile(rb"(\d+)\s+(\d+)\s+obj(.*?)endobj", re.S)
_STREAM_RE = re.compile(rb"stream\r?\n(.*?)\r?\nendstream", re.S)


def _pdf_images(data: bytes) -> List[bytes]:
    """Embedded /Subtype /Image XObjects → encoded image bytes.

    DCTDecode streams are JPEG as-is; FlateDecode RGB/Gray rasters are
    re-encoded as PNG via Pillow. Other filters (JBIG2, CCITT) are skipped.
    """
    images: List[bytes] = []
    for m in _OBJ_RE.finditer(data):
        body = m.group(3)
        if b"/Subtype" not in body or b"/Image" not in body:
            continue
        sm = _STREAM_RE.search(body)
        if not sm:
            continue
        stream = sm.group(1)
        if b"DCTDecode" in body:
            images.append(stream)  # JPEG bytes
            continue
        if b"FlateDecode" in body:
            try:
                raw = zlib.decompress(stream)
            except zlib.error:
                continue
            wm = re.search(rb"/Width\s+(\d+)", body)
            hm = re.search(rb"/Height\s+(\d+)", body)
            if not (wm and hm):
                continue
            w, h = int(wm.group(1)), int(hm.group(1))
            mode = None
            if len(raw) == w * h * 3:
                mode = "RGB"
            elif len(raw) == w * h:
                mode = "L"
            elif len(raw) == w * h * 4:
                mode = "CMYK"
            if mode is None:
                continue
            try:
                from PIL import Image

                img = Image.frombytes(mode, (w, h), raw)
                buf = io.BytesIO()
                img.convert("RGB").save(buf, format="PNG")
                images.append(buf.getvalue())
            except Exception as exc:
                logger.debug("skipping undecodable PDF image: %s", exc)
    return images


def parse_pdf(path: str) -> List[Element]:
    """Text blocks (via loaders.load_pdf) + embedded images
    (ref get_pdf_documents, custom_pdf_parser.py:312-370)."""
    from generativeaiexamples_tpu.chains.loaders import load_pdf

    name = os.path.basename(path)
    elements: List[Element] = []
    text = load_pdf(path)
    if text.strip():
        elements.append(Element(kind="text", text=text,
                                metadata={"source": name}))
    with open(path, "rb") as fh:
        data = fh.read()
    for i, img in enumerate(_pdf_images(data)):
        elements.append(Element(
            kind="image", image_bytes=img,
            metadata={"source": name, "image_index": str(i)}))
    return elements


# ------------------------------------------------------------------ PPTX

_A_NS = "{http://schemas.openxmlformats.org/drawingml/2006/main}"


def parse_pptx(path: str) -> List[Element]:
    """Slide text runs (<a:t>) + embedded media
    (ref custom_powerpoint_parser.py — python-pptx equivalent)."""
    name = os.path.basename(path)
    elements: List[Element] = []
    with zipfile.ZipFile(path) as zf:
        slides = sorted(
            (n for n in zf.namelist()
             if re.fullmatch(r"ppt/slides/slide\d+\.xml", n)),
            key=lambda n: int(re.search(r"\d+", os.path.basename(n)).group()))
        for slide_name in slides:
            slide_no = re.search(r"\d+", os.path.basename(slide_name)).group()
            try:
                root = ET.fromstring(zf.read(slide_name))
            except ET.ParseError:
                continue
            runs = [el.text for el in root.iter(f"{_A_NS}t") if el.text]
            if runs:
                elements.append(Element(
                    kind="text", text="\n".join(runs),
                    metadata={"source": name, "slide": slide_no}))
        for media in zf.namelist():
            if media.startswith("ppt/media/") and media.lower().endswith(
                    (".png", ".jpg", ".jpeg")):
                elements.append(Element(
                    kind="image", image_bytes=zf.read(media),
                    metadata={"source": name,
                              "media": os.path.basename(media)}))
    return elements


# ----------------------------------------------------------------- image


def parse_image(path: str) -> List[Element]:
    """Standalone image file (ref custom_img_parser.py)."""
    with open(path, "rb") as fh:
        data = fh.read()
    return [Element(kind="image", image_bytes=data,
                    metadata={"source": os.path.basename(path)})]


_PARSERS = {".pdf": parse_pdf, ".pptx": parse_pptx, ".png": parse_image,
            ".jpg": parse_image, ".jpeg": parse_image}


def parse_multimodal(path: str) -> List[Element]:
    ext = os.path.splitext(path)[1].lower()
    parser = _PARSERS.get(ext)
    if parser is None:
        raise ValueError(f"{os.path.basename(path)} is not a valid "
                         f"PDF/PPTX/PNG file")
    return parser(path)


def image_summary(image_bytes: bytes) -> Optional[str]:
    """Deterministic structural description used by the stub describer:
    dimensions + dominant-color characterization via Pillow."""
    try:
        from PIL import Image

        img = Image.open(io.BytesIO(image_bytes)).convert("RGB")
    except Exception as exc:
        logger.debug("image summary skipped (undecodable image): %s", exc)
        return None
    w, h = img.size
    import numpy as np

    small = np.asarray(img.resize((8, 8)), dtype=np.float32)
    r, g, b = (int(c) for c in small.reshape(-1, 3).mean(axis=0))
    lum = (r + g + b) // 3
    tone = "dark" if lum < 85 else ("light" if lum > 170 else "mid-tone")
    return f"{w}x{h} {tone} image (mean RGB {r},{g},{b})"
