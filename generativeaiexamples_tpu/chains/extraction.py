"""Structured text extraction — schema-validated JSON from free text.

Behavioral parity with the reference's structured-text-extraction vision
workflow (ref: vision_workflows/README.md:25-37 — "Structured Text
Extraction": run a VLM/LLM over documents and pull typed fields into a
fixed schema). The extraction loop is model-agnostic here: text arrives
from the document parsers (chains/multimodal_parsers.py for images/PDFs)
or straight from the caller, the in-proc LLM fills the schema, and a
validation-and-retry loop feeds type errors back to the model instead of
returning malformed records (the workflow's schema box, minus the hosted
NIM).
"""

from __future__ import annotations

import dataclasses
import json
import logging
from typing import Any, Dict, List, Optional, Sequence

from generativeaiexamples_tpu.chains.query_decomposition import extract_json

logger = logging.getLogger(__name__)

_TYPES = {
    "string": str,
    "number": (int, float),
    "boolean": bool,
    "list": list,
}


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    type: str = "string"            # string | number | boolean | list
    description: str = ""
    required: bool = True

    def __post_init__(self):
        if self.type not in _TYPES:
            raise ValueError(f"unknown field type {self.type!r}; "
                             f"valid: {sorted(_TYPES)}")


PROMPT = """\
Extract the following fields from the text. Reply with ONLY a JSON object.
Use null for a missing optional field. Fields:
{fields}

Text:
{text}
"""


def _render_fields(fields: Sequence[Field]) -> str:
    lines = []
    for f in fields:
        req = "required" if f.required else "optional"
        desc = f" — {f.description}" if f.description else ""
        lines.append(f'  "{f.name}": {f.type} ({req}){desc}')
    return "\n".join(lines)


def _validate(obj: Dict[str, Any], fields: Sequence[Field]) -> List[str]:
    """Type/presence errors, phrased for the retry prompt."""
    errors = []
    for f in fields:
        value = obj.get(f.name)
        if value is None:
            if f.required:
                errors.append(f'missing required field "{f.name}"')
            continue
        expected = _TYPES[f.type]
        if f.type == "number" and isinstance(value, bool):
            errors.append(f'"{f.name}" must be a number, got boolean')
        elif not isinstance(value, expected):
            errors.append(f'"{f.name}" must be {f.type}, '
                          f"got {type(value).__name__}")
    return errors


class StructuredExtractor:
    """LLM extraction with schema validation + error-feedback retries."""

    def __init__(self, llm, max_retries: int = 2) -> None:
        self.llm = llm
        self.max_retries = max_retries

    def extract(self, text: str, fields: Sequence[Field]
                ) -> Dict[str, Any]:
        """Typed record for ``fields``; raises ValueError after the retry
        budget (never returns a record that fails its own schema)."""
        messages = [{"role": "user", "content": PROMPT.format(
            fields=_render_fields(fields), text=text)}]
        errors: List[str] = []
        for attempt in range(self.max_retries + 1):
            reply = "".join(self.llm.chat(messages, max_tokens=512,
                                          temperature=0.0))
            obj = extract_json(reply)
            if obj is None:
                # reset per attempt — stale type errors from an earlier
                # reply must not masquerade as this one's problem
                errors = ["no JSON object in reply"]
            else:
                errors = _validate(obj, fields)
                if not errors:
                    return {f.name: obj.get(f.name) for f in fields}
            if attempt < self.max_retries:
                logger.info("extraction attempt %d invalid: %s",
                            attempt + 1, errors)
                messages = messages + [
                    {"role": "assistant", "content": reply},
                    {"role": "user",
                     "content": "That reply was invalid: "
                                + "; ".join(errors)
                                + ". Reply again with ONLY a corrected "
                                  "JSON object."}]
        raise ValueError(f"extraction failed after {self.max_retries + 1} "
                         f"attempts: {'; '.join(errors)}")

    def extract_many(self, texts: Sequence[str], fields: Sequence[Field]
                     ) -> List[Optional[Dict[str, Any]]]:
        """Batch helper: None for records that exhausted their retries
        (a failed page must not abort a document batch)."""
        out: List[Optional[Dict[str, Any]]] = []
        for text in texts:
            try:
                out.append(self.extract(text, fields))
            except ValueError as exc:
                logger.warning("extraction skipped a record: %s", exc)
                out.append(None)
        return out
