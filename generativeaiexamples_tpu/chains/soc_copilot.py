"""SOC analyst copilot: digital-fingerprint anomaly detection + agent.

In-tree analogue of the reference's digital-human security analyst
(ref: community/digital-human-security-analyst/ — Morpheus Digital
Fingerprinting per-user autoencoders score event logs, flagged events
become LLM alert summaries in a database, and a langchain agent with SOC
tools — network traffic, user directory, threat intel, alert summaries —
answers the analyst; the speech/face layers are served by the in-tree
voice loop). TPU-first redesign of the DFP core: ONE jitted train step
fits every user's tiny autoencoder simultaneously (`vmap` over the user
axis — Morpheus trains per-user models serially in torch), so a fleet of
per-entity fingerprints trains in a handful of fused dispatches.

Event features (hour-of-day on the circle, app/location hashes, outcome,
byte volume) deliberately mirror the DFP azure/duo feature sets at demo
scale; the anomaly score is the autoencoder's reconstruction error in
z-units of the user's own training distribution — "unusual FOR THIS USER",
the property that distinguishes DFP from global outlier detection.
"""

from __future__ import annotations

import hashlib
import json
import math
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.chains.tool_agent import Tool, ToolAgent

FEATS = 12


def _stable_hash(kind: str, value: str) -> float:
    """Process-independent categorical hash feature in [0, 1) — builtin
    ``hash()`` is randomized per process (PYTHONHASHSEED), which would make
    fingerprints trained in one process disagree with scoring in another."""
    digest = hashlib.blake2b(f"{kind}:{value}".encode("utf-8"),
                             digest_size=4).digest()
    return (int.from_bytes(digest, "little") % 997) / 997.0


def _featurize(ev: Dict[str, Any]) -> np.ndarray:
    """One auth/network event → a fixed feature vector."""
    hour = float(ev.get("hour", 0.0))
    ang = 2 * math.pi * hour / 24.0
    app_h = _stable_hash("app", ev.get("app", ""))
    loc_h = _stable_hash("loc", ev.get("location", ""))
    dev_h = _stable_hash("dev", ev.get("device", ""))
    mb = float(ev.get("bytes_mb", 0.0))
    return np.asarray([
        math.sin(ang), math.cos(ang),
        app_h, loc_h, dev_h,
        1.0 if ev.get("success", True) else 0.0,
        math.log1p(mb) / 10.0,
        1.0 if ev.get("admin", False) else 0.0,
        1.0 if ev.get("vpn", False) else 0.0,
        1.0 if ev.get("new_device", False) else 0.0,
        float(ev.get("failures_last_hour", 0)) / 10.0,
        1.0,
    ], np.float32)


def _init_ae(key, hidden: int = 4):
    k1, k2 = jax.random.split(key)
    s = 1.0 / math.sqrt(FEATS)
    return {"w1": jax.random.normal(k1, (FEATS, hidden)) * s,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k2, (hidden, FEATS)) * s,
            "b2": jnp.zeros((FEATS,))}


def _recon(p, x):
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    return h @ p["w2"] + p["b2"]


def _loss(p, x, mask):
    err = ((_recon(p, x) - x) ** 2).mean(axis=-1)
    return (err * mask).sum() / jnp.maximum(mask.sum(), 1.0)


@jax.jit
def _train_all(params, xs, masks, lr: float = 5e-2, steps: int = 300):
    """Fit EVERY user's autoencoder in one compiled program: the grad
    step is vmapped over the leading user axis and scanned over epochs."""

    def one_step(params, _):
        def per_user(p, x, m):
            g = jax.grad(_loss)(p, x, m)
            return jax.tree.map(lambda a, b: a - lr * b, p, g)

        return jax.vmap(per_user)(params, xs, masks), None

    params, _ = jax.lax.scan(one_step, params, None, length=steps)
    return params


@jax.jit
def _scores(params, xs):
    def per_user(p, x):
        return ((_recon(p, x) - x) ** 2).mean(axis=-1)

    return jax.vmap(per_user)(params, xs)


@dataclass
class Fingerprints:
    """Per-user behavioral models + their training error statistics."""

    users: List[str]
    params: Any
    mu: np.ndarray                 # (U,) mean train reconstruction error
    sd: np.ndarray                 # (U,)

    @staticmethod
    def fit(history: Dict[str, List[Dict[str, Any]]],
            seed: int = 0) -> "Fingerprints":
        users = sorted(history)
        maxn = max(len(v) for v in history.values())
        xs = np.zeros((len(users), maxn, FEATS), np.float32)
        masks = np.zeros((len(users), maxn), np.float32)
        for u, name in enumerate(users):
            evs = history[name]
            for i, ev in enumerate(evs):
                xs[u, i] = _featurize(ev)
                masks[u, i] = 1.0
        keys = jax.random.split(jax.random.PRNGKey(seed), len(users))
        params = jax.vmap(_init_ae)(keys)
        params = _train_all(params, jnp.asarray(xs), jnp.asarray(masks))
        errs = np.asarray(_scores(params, jnp.asarray(xs)))
        mu = np.zeros(len(users), np.float32)
        sd = np.ones(len(users), np.float32)
        for u in range(len(users)):
            e = errs[u][masks[u] > 0]
            mu[u] = e.mean()
            sd[u] = max(float(e.std()), 1e-4)
        return Fingerprints(users=users, params=params, mu=mu, sd=sd)

    def score(self, user: str, events: Sequence[Dict[str, Any]]
              ) -> List[float]:
        """Z-scored reconstruction error of each event under the USER'S
        OWN model — "unusual for them", not globally unusual."""
        u = self.users.index(user)
        x = np.stack([_featurize(e) for e in events]).astype(np.float32)
        p = jax.tree.map(lambda a: a[u], self.params)
        err = np.asarray(((_recon(p, jnp.asarray(x)) - x) ** 2).mean(-1))
        return [float((e - self.mu[u]) / self.sd[u]) for e in err]


@dataclass
class Alert:
    user: str
    z: float
    event: Dict[str, Any]
    summary: str
    ts: float = field(default_factory=time.time)


class AlertStore:
    """Alert-summaries database (ref: the copilot's Alert Summaries DB fed
    by DFP + an LLM NIM). Summaries come from the provided ``summarize``
    callable — an LLM when one is wired in, a deterministic template
    otherwise (tests, air-gapped ops)."""

    def __init__(self, summarize: Optional[Callable[[str], str]] = None
                 ) -> None:
        self._alerts: List[Alert] = []
        self._summarize = summarize

    def ingest(self, fp: Fingerprints, user: str,
               events: Sequence[Dict[str, Any]],
               z_threshold: float = 3.0) -> List[Alert]:
        out = []
        for ev, z in zip(events, fp.score(user, events)):
            if z < z_threshold:
                continue
            base = (f"Anomalous activity for user {user}: "
                    f"app={ev.get('app')} location={ev.get('location')} "
                    f"hour={ev.get('hour')} bytes_mb={ev.get('bytes_mb')} "
                    f"(z={z:.1f} vs their own baseline)")
            summary = self._summarize(base) if self._summarize else base
            alert = Alert(user=user, z=z, event=dict(ev), summary=summary)
            self._alerts.append(alert)
            out.append(alert)
        return out

    def query(self, user: str = "", limit: int = 10) -> List[Alert]:
        hits = [a for a in self._alerts if not user or a.user == user]
        return sorted(hits, key=lambda a: -a.z)[:limit]


def soc_tools(alerts: AlertStore, directory: Dict[str, Dict[str, Any]],
              threat_intel: Dict[str, str],
              traffic: List[Dict[str, Any]]) -> List[Tool]:
    """The analyst agent's tool belt (ref: agent_tools.py — Network
    Traffic DB, User Directory, Threat Intelligence, Alert Summaries)."""

    def alerts_fn(user: str = "") -> str:
        return json.dumps([{"user": a.user, "z": round(a.z, 1),
                            "summary": a.summary}
                           for a in alerts.query(user)])

    def directory_fn(user: str) -> str:
        return json.dumps(directory.get(user, {"error": "unknown user"}))

    def intel_fn(indicator: str) -> str:
        return json.dumps({"indicator": indicator,
                           "intel": threat_intel.get(
                               indicator, "no intel on this indicator")})

    def traffic_fn(user: str) -> str:
        return json.dumps([t for t in traffic if t.get("user") == user][:20])

    u = {"type": "object", "properties": {"user": {"type": "string"}},
         "required": ["user"]}
    return [
        Tool(name="query_alerts",
             description="Recent DFP anomaly alert summaries, highest "
                         "severity first; optional user filter.",
             parameters={"type": "object",
                         "properties": {"user": {"type": "string"}}},
             fn=alerts_fn),
        Tool(name="user_directory",
             description="Role, department, manager and normal working "
                         "hours of a user.",
             parameters=u, fn=directory_fn),
        Tool(name="threat_intel",
             description="Threat-intelligence lookup for an indicator "
                         "(IP, domain, file hash).",
             parameters={"type": "object", "properties": {
                 "indicator": {"type": "string"}},
                 "required": ["indicator"]}, fn=intel_fn),
        Tool(name="network_traffic",
             description="Recent network flows for a user.",
             parameters=u, fn=traffic_fn),
    ]


def build_copilot(llm, alerts: AlertStore, directory, threat_intel,
                  traffic, max_steps: int = 6) -> ToolAgent:
    """The analyst-facing agent: multi-step tool reasoning over the SOC
    stores (speech in/out rides the playground voice loop)."""
    return ToolAgent(
        llm, soc_tools(alerts, directory, threat_intel, traffic),
        max_steps=max_steps,
        system_prompt=(
            "You are a SOC analyst copilot. Triage alerts with the "
            "tools: check the user's directory entry and recent "
            "traffic, consult threat intel for indicators, and give "
            "a verdict (false positive vs escalate) with reasons."))
