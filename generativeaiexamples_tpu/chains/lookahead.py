"""Lookahead retrieval: overlap embed+search with an in-flight LLM call.

TeleRAG's observation (arxiv 2502.20969): when a RAG chain runs an LLM call
whose OUTPUT becomes the retrieval query (condense-the-question, rewrite,
routing), the retrieval latency can be hidden by speculatively retrieving on
the RAW query while that call is generating, then reconciling once the
rewritten query lands — rewritten queries usually stay close to the raw one,
so the speculative hits are usually the right hits.

`LookaheadRetrieval` wraps that pattern with the same futures shape the
engine scheduler uses for dispatch-ahead decode:

  * ``start(query)`` kicks the work fn (embed + search, caller-supplied) onto
    a pool thread and returns immediately — the caller then runs its LLM call;
  * ``reconcile(final_query)`` joins the future. Identical query → reuse.
    Otherwise the final query is embedded and compared against the raw
    query's vector (both L2-normalized, so the dot IS the cosine): above
    ``sim_threshold`` the speculative hits are reused, below it the chain
    retrieves again with the final vector — correctness never depends on
    the speculation.

Observability (core/metrics.py):

  * ``lookahead_reuse`` / ``lookahead_requery`` counters — how often the
    speculation paid off;
  * ``retrieval_overlap_frac`` histogram — fraction of the speculative
    retrieval's latency hidden behind the overlapped LLM call (1.0 = the
    retrieval was entirely free).
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Optional, Tuple

import numpy as np

from generativeaiexamples_tpu.core.metrics import REGISTRY

logger = logging.getLogger(__name__)

# Shared pool for speculative retrievals and sibling pipeline stages. Sized
# well past typical request concurrency: a worker BLOCKS for a full encoder
# dispatch (~100 ms on a remote-attached chip), and a multi-turn request can
# hold 3 at once (lookahead + two funnels) — an undersized pool would queue
# funnel thunks past the micro-batcher's wait window and break the very
# coalescing it feeds. Threads here are cheap (they sleep on futures/GIL).
_POOL = ThreadPoolExecutor(max_workers=32, thread_name_prefix="rag-lookahead")

# cosine floor for reusing speculative hits under a rewritten query —
# e5-class normalized embeddings of a question and its standalone rewrite
# sit well above this; genuinely re-scoped questions fall below
DEFAULT_SIM_THRESHOLD = 0.85


class LookaheadRetrieval:
    """One speculative retrieval, started before / reconciled after an LLM
    call. ``retrieve(query, qvec=None)`` maps a query string to
    ``(qvec, payload)``: ``qvec`` is the L2-normalized query embedding used
    for the similarity reconcile (may be None to force exact-match-only
    reuse) and ``payload`` is whatever the chain needs (hits, pools, …).
    When reconcile re-retrieves after a failed similarity gate it passes
    the final query's ALREADY-COMPUTED embedding back as ``qvec`` so the
    retrieve fn must not embed it a second time."""

    def __init__(self, retrieve: Callable[..., Tuple[Optional[np.ndarray], Any]],
                 sim_threshold: float = DEFAULT_SIM_THRESHOLD) -> None:
        self._retrieve = retrieve
        self.sim_threshold = sim_threshold
        self._query: Optional[str] = None
        self._future: Optional[Future] = None
        self._started_at = 0.0

    def start(self, query: str) -> "LookaheadRetrieval":
        self._query = query
        self._started_at = time.perf_counter()
        self._future = _POOL.submit(self._timed_retrieve, query)
        return self

    def seed(self, query: str,
             result: Tuple[Optional[np.ndarray], Any]) -> "LookaheadRetrieval":
        """Adopt an ALREADY-COMPUTED retrieval for ``query`` as the
        speculation — zero new encoder/store work. Used when the caller is
        holding this query's hits and is about to run an LLM call that may
        rewrite the query (the agentic chain's retry paths): reconcile()
        then reuses or re-retrieves exactly as it would for start()."""
        self._query = query
        self._started_at = time.perf_counter()
        fut: Future = Future()
        fut.set_result((result, 0.0))   # spec_wall 0: nothing was overlapped
        self._future = fut
        return self

    def _timed_retrieve(self, query: str):
        t0 = time.perf_counter()
        result = self._retrieve(query)
        return result, time.perf_counter() - t0

    def reconcile(self, final_query: str,
                  embed: Optional[Callable[[str], np.ndarray]] = None
                  ) -> Tuple[Optional[np.ndarray], Any]:
        """Join the speculation and return ``(qvec, payload)`` valid for
        ``final_query``. ``embed`` (query text → normalized vector) is
        required for similarity-based reuse of a REWRITTEN query; without
        it only an exact match reuses the speculation."""
        assert self._future is not None, "reconcile() before start()"
        llm_wall = time.perf_counter() - self._started_at
        try:
            (qvec, payload), spec_wall = self._future.result()
        except Exception as exc:   # noqa: BLE001 — speculation is best-effort
            # correctness never depends on the speculation: a failure there
            # (e.g. a poisoned co-batched encoder dispatch, or the batcher
            # closing during shutdown) must not fail the REQUEST — retrieve
            # fresh on the final query instead
            logger.warning("speculative retrieval failed (%s); retrieving "
                           "on the final query", exc)
            REGISTRY.counter("lookahead_requery").inc()
            return self._retrieve(final_query)
        if spec_wall > 0:
            REGISTRY.histogram("retrieval_overlap_frac").observe(
                min(1.0, llm_wall / spec_wall))
        if final_query == self._query:
            REGISTRY.counter("lookahead_reuse").inc()
            return qvec, payload
        fvec: Optional[np.ndarray] = None
        # an unsatisfiable threshold (> 1.0 for normalized vectors, the
        # exact-match-only mode) must not burn an embed dispatch on a gate
        # that cannot pass
        if qvec is not None and embed is not None and self.sim_threshold <= 1.0:
            fvec = np.asarray(embed(final_query))
            sim = float(np.dot(fvec, qvec))
            if sim >= self.sim_threshold:
                # the rewrite stayed on-topic: the speculative hits stand
                # (TeleRAG's common case), and the final query's OWN vector
                # is the honest one to carry forward
                REGISTRY.counter("lookahead_reuse").inc()
                return fvec, payload
        REGISTRY.counter("lookahead_requery").inc()
        # pass the already-computed final vector along (if any) so the
        # retrieval does not embed the same string twice
        return self._retrieve(final_query, fvec)


def submit_concurrently(*thunks: Callable[[], Any]) -> list:
    """Run the thunks on the lookahead pool and join in order — used to
    issue sibling pipeline stages (e.g. the two rerank funnels of the
    multi-turn chain) at the same time so the encoder micro-batcher can
    coalesce them into one TPU dispatch. Every future is awaited before any
    exception re-raises: a failing sibling must not leave the others
    running unobserved (their exceptions would otherwise surface only as
    GC-time 'never retrieved' warnings)."""
    futures = [_POOL.submit(t) for t in thunks]
    results: list = []
    first_exc: Optional[BaseException] = None
    for f in futures:
        try:
            results.append(f.result())
        # tpulint: disable=except-swallow -- gather pattern: the first
        # exception re-raises after every sibling future is observed
        except BaseException as exc:   # noqa: BLE001 — re-raised below
            if first_exc is None:
                first_exc = exc
            results.append(None)
    if first_exc is not None:
        raise first_exc
    return results
