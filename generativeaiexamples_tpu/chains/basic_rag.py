"""basic_rag — ingest→split→embed→store; retrieve→prompt→stream.

Behavioral parity with the reference's flagship example
(ref: RAG/examples/basic_rag/langchain/chains.py): `ingest_docs` loads and
chunks the file then indexes it (chains.py:54-88); `rag_chain` retrieves
top-k above the score threshold, trims context to the token budget, builds
the RAG prompt, and streams (chains.py:121-192 + retriever wiring 156-167;
budget DEFAULT_MAX_CONTEXT utils.py:103). `llm_chain` answers without
retrieval (chains.py:91-118).

The pipeline differences are architectural, not behavioral: embedding and
generation are in-process TPU calls instead of HTTP hops to NIM containers.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Iterator, List, Sequence

from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.chains.loaders import load_document
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.observability.otel import stage_span
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server import guardrails
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

COLLECTION = "basic_rag"


def _sampling(llm_settings: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "max_tokens": int(llm_settings.get("max_tokens", 256)),
        "temperature": float(llm_settings.get("temperature", 0.2)),
        "top_p": float(llm_settings.get("top_p", 0.7)),
        "stop": list(llm_settings.get("stop") or []),
    }


def trim_context(chunks: Sequence[str], tokenizer, budget: int) -> str:
    """Concatenate retrieved chunks up to the token budget
    (ref: LimitRetrievedNodesLength._postprocess_nodes, utils.py:106-134)."""
    used = 0
    kept: List[str] = []
    for chunk in chunks:
        n = len(tokenizer.encode(chunk))
        if used + n > budget:
            break
        kept.append(chunk)
        used += n
    return "\n\n".join(kept)


@register_example("basic_rag")
class BasicRAG(BaseExample):
    # subclasses point the same chain at their own collection
    # (e.g. chains/asr_stream_rag.py's live-transcript store)
    collection = COLLECTION

    def __init__(self, context: ChainContext = None) -> None:
        self.ctx = context or get_context()

    # ------------------------------------------------------------ ingestion

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        text = load_document(filepath)
        if not text.strip():
            raise ValueError(f"no text extracted from {filename}")
        chunks = self.ctx.splitter().split(text)
        docs = [Document(content=c, metadata={"source": filename})
                for c in chunks]
        embeddings = self.ctx.embedder.embed_documents([d.content for d in docs])
        self.ctx.store(self.collection).add(docs, embeddings)
        logger.info("ingested %s: %d chunks", filename, len(docs))

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        messages = ([{"role": "system", "content": self.ctx.prompts["chat_template"]}]
                    + list(chat_history) + [{"role": "user", "content": query}])
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        rcfg = self.ctx.config.retriever
        # stage spans + stage_<name>_s histograms (observability/otel.py):
        # the per-request view of the pipelined dataplane — embed rides the
        # cross-request micro-batcher, so concurrent requests share dispatches
        with stage_span("embed"):
            qvec = self.ctx.embedder.embed_queries([query])[0]
        with stage_span("retrieve"):
            hits = self.ctx.store(self.collection).search(
                qvec, top_k=rcfg.top_k, score_threshold=rcfg.score_threshold)
        context_text = trim_context([d.content for d, _ in hits],
                                    self.ctx.embedder.tokenizer,
                                    rcfg.max_context_tokens)
        guardrails.record_context(context_text)
        system = self.ctx.prompts["rag_template"].format(context=context_text)
        messages = ([{"role": "system", "content": system}]
                    + list(chat_history) + [{"role": "user", "content": query}])
        with stage_span("generate"):
            yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    # ------------------------------------------------------------ documents

    def document_search(self, query: str, num_docs: int = 4) -> List[Dict[str, Any]]:
        qvec = self.ctx.embedder.embed_queries([query])[0]
        hits = self.ctx.store(self.collection).search(
            qvec, top_k=num_docs,
            score_threshold=self.ctx.config.retriever.score_threshold)
        return [{"source": str(d.metadata.get("source", "")),
                 "content": d.content, "score": score}
                for d, score in hits]

    def get_documents(self) -> List[str]:
        return self.ctx.store(self.collection).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> bool:
        return self.ctx.store(self.collection).delete_by_source(filenames) > 0
