"""Streaming ASR → RAG: live audio becomes a queryable knowledge base.

In-tree counterpart of the community FM-ASR streaming RAG app (ref:
community/fm-asr-streaming-rag/README.md — SDR/file-replay audio → Riva
ASR NIM → transcripts into Milvus via the embedding NIM → RAG Q&A), built
from pieces this framework already ships:

  * audio arrives as PCM blocks (an SDR demodulator, a file replayer, or
    the playground's mic stream — anything yielding bytes);
  * transcription runs the speech seam (speech/clients.py): the in-tree
    whisper model (zero external services) or an HTTP ASR endpoint;
  * timestamped transcript SEGMENTS flow through the bounded streaming
    ingest pipeline (retrieval/streaming_ingest.py: chunk → embed → store),
    exactly like any other live document source;
  * Q&A is the standard RAG chain over the live collection — ask about
    what was just said on air.

The reference needs five containers and two GPUs for this loop; here it is
one process on the TPU stack.
"""

from __future__ import annotations

import logging
import time
from typing import AsyncIterator, Iterator, Optional

from generativeaiexamples_tpu.chains.basic_rag import BasicRAG
from generativeaiexamples_tpu.chains.context import ChainContext
from generativeaiexamples_tpu.retrieval.streaming_ingest import (
    SourceItem, StreamingIngestor)
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

COLLECTION = "asr_stream"


class TranscriptSegmenter:
    """Turn a stream of PCM16 audio blocks into timestamped transcript
    segments.

    Audio accumulates until ``segment_seconds`` of samples arrived, then the
    buffered window is transcribed as ONE unit and emitted with its
    [t0, t1) span — the granularity documents enter the vector store at
    (the reference chunks transcripts the same way before Milvus). Bounded
    work: each flush transcribes only its own window, not the whole
    history, so an endless broadcast costs O(1) memory and O(n) ASR."""

    def __init__(self, asr, segment_seconds: float = 15.0,
                 sample_rate: int = 16000, station: str = "stream",
                 language: str = "en-US",
                 collection: str = COLLECTION) -> None:
        self.asr = asr
        self.segment_bytes = int(segment_seconds * sample_rate) * 2
        self.sample_rate = sample_rate
        self.station = station
        self.language = language
        self.collection = collection
        self._buf = bytearray()
        self._consumed_bytes = 0       # audio-time bookkeeping

    def _span(self, n_bytes: int) -> tuple:
        t0 = self._consumed_bytes / (2 * self.sample_rate)
        t1 = (self._consumed_bytes + n_bytes) / (2 * self.sample_rate)
        return t0, t1

    def _wav(self, data: bytes) -> bytes:
        """Wrap the raw PCM window in a WAV header carrying the stream's
        sample rate — the ASR contract is headered audio (headerless bytes
        would be ASSUMED 16 kHz by the whisper backend, transcribing any
        other rate as slowed/sped garbage with no visible failure)."""
        import io
        import wave
        buf = io.BytesIO()
        with wave.open(buf, "wb") as w:
            w.setnchannels(1)
            w.setsampwidth(2)
            w.setframerate(self.sample_rate)
            w.writeframes(data)
        return buf.getvalue()

    def _emit(self, data: bytes) -> Optional[SourceItem]:
        t0, t1 = self._span(len(data))
        self._consumed_bytes += len(data)
        try:
            text = self.asr.transcribe(self._wav(data),
                                       self.language).strip()
        # tpulint: disable=except-swallow -- a dead ASR must be visible in
        # stats: the error rides the SourceItem and lands in stats.errors
        except Exception as exc:
            return SourceItem(content="", source=self.station,
                              collection=self.collection,
                              error=f"asr failed at {t0:.1f}s: {exc}")
        if not text:
            return None                # silence window: nothing to index
        return SourceItem(
            content=f"[{self.station} {t0:.1f}s-{t1:.1f}s] {text}",
            source=f"{self.station}@{t0:.1f}s", collection=self.collection)

    def feed(self, block: bytes) -> Iterator[SourceItem]:
        """Add an audio block; yields a segment per completed window."""
        self._buf.extend(block)
        while len(self._buf) >= self.segment_bytes:
            window = bytes(self._buf[: self.segment_bytes])
            del self._buf[: self.segment_bytes]
            item = self._emit(window)
            if item is not None:
                yield item

    def finalize(self) -> Iterator[SourceItem]:
        """Flush the trailing partial window (end of broadcast/file)."""
        if self._buf:
            data = bytes(self._buf)
            self._buf.clear()
            item = self._emit(data)
            if item is not None:
                yield item


async def asr_source(blocks: AsyncIterator[bytes], asr,
                     segment_seconds: float = 15.0,
                     sample_rate: int = 16000,
                     station: str = "stream",
                     collection: str = COLLECTION
                     ) -> AsyncIterator[SourceItem]:
    """Adapt an async stream of PCM16 blocks into streaming-ingest source
    items via :class:`TranscriptSegmenter` (the shape
    `StreamingIngestor.run` consumes alongside file/jsonl sources). The
    ASR work runs off the event loop (asyncio.to_thread) so the chunk/
    embed/store stages keep flowing during a window's transcription —
    the same posture as every other stage in streaming_ingest.py."""
    import asyncio

    seg = TranscriptSegmenter(asr, segment_seconds=segment_seconds,
                              sample_rate=sample_rate, station=station,
                              collection=collection)
    async for block in blocks:
        for item in await asyncio.to_thread(lambda b=block: list(seg.feed(b))):
            yield item
    for item in await asyncio.to_thread(lambda: list(seg.finalize())):
        yield item


@register_example("asr_stream_rag")
class ASRStreamRAG(BasicRAG):
    """RAG over live transcripts: the standard retrieve→prompt→stream chain
    pointed at the streaming-ASR collection. `ingest_stream` drives audio
    sources through the bounded pipeline; `/generate` with
    use_knowledge_base answers questions about what was broadcast."""

    collection = COLLECTION

    def __init__(self, context: ChainContext = None) -> None:
        super().__init__(context)

    def ingest_stream(self, blocks: AsyncIterator[bytes], asr,
                      segment_seconds: float = 15.0,
                      sample_rate: int = 16000,
                      station: str = "stream"):
        """Run one audio stream to exhaustion into the live collection;
        returns IngestStats. Callable repeatedly (multiple stations →
        multiple calls or one call per source list)."""
        ingestor = StreamingIngestor(
            embedder=self.ctx.embedder,
            store_factory=self.ctx.store,
            splitter=self.ctx.splitter())
        src = asr_source(blocks, asr, segment_seconds=segment_seconds,
                         sample_rate=sample_rate, station=station,
                         collection=self.collection)
        return ingestor.run_sync([src])
