"""router_rag — query routing across local KB, web seam, and direct LLM.

Behavioral parity with the reference's routing workflow
(ref: community/routing-multisource-rag/workflow.py — QueryFlow: a routing
step picks sources (`RoutingChoice`, line 59), `rewrite_query` (160)
reformulates for retrieval, then Milvus retrieval and a Perplexity web call
run as parallel branches (`milvus_retrieve`:202, PerplexityQueryEvent),
nodes are collected and synthesized with source attributions). The
LlamaIndex event workflow is replaced by a plain staged pipeline; Milvus by
the in-proc TPU store; Perplexity by a pluggable `WebSearchClient` seam
(zero-egress default returns nothing gracefully, matching the app's
behavior with no PERPLEXITY_API_KEY).

Routing decisions are LLM-emitted JSON parsed defensively; unparseable
output degrades to the KB route (never a dead end).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterator, List, Optional, Sequence

from generativeaiexamples_tpu.chains.basic_rag import _sampling, trim_context
from generativeaiexamples_tpu.server import guardrails
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.chains.loaders import load_document
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

COLLECTION = "router_rag"

ROUTE_PROMPT = """\
You route user questions to data sources. Sources:
  "kb"     - the local document knowledge base (ingested files)
  "web"    - live web search (recent events, external facts)
  "direct" - no retrieval needed (small talk, general knowledge, math)
Reply with ONLY a JSON object:
{{"sources": ["kb"|"web"|"direct", ...], "rewritten": "<standalone search query>"}}

Question: {query}
"""

SYNTH_PROMPT = """\
Answer the user's question from the sources below. Attribute facts to their
source tag ([kb] or [web]) when they matter. If the sources do not contain
the answer, say so.

{context}
"""


class WebSearchClient:
    """Seam for the reference's Perplexity branch (workflow.py web route).
    The default implementation returns no results — the zero-egress
    analogue of running the app without PERPLEXITY_API_KEY. Deployments
    point `search` at any HTTP search/answer API."""

    def search(self, query: str, max_results: int = 3) -> List[Dict[str, str]]:
        logger.info("web search seam inactive; skipping web route")
        return []


def parse_route(text: str) -> Dict[str, Any]:
    """Defensive parse of the routing JSON; degrade to the KB route."""
    from generativeaiexamples_tpu.chains.query_decomposition import (
        extract_json)

    obj = extract_json(text)
    if isinstance(obj, dict):
        try:
            sources = [s for s in obj.get("sources", [])
                       if s in ("kb", "web", "direct")]
        except TypeError:
            sources = []
        if sources:
            return {"sources": sources,
                    "rewritten": str(obj.get("rewritten", "")).strip()}
    return {"sources": ["kb"], "rewritten": ""}


@register_example("router_rag")
class RouterRAG(BaseExample):
    def __init__(self, context: ChainContext = None,
                 web_client: Optional[WebSearchClient] = None) -> None:
        self.ctx = context or get_context()
        self.web = web_client or WebSearchClient()

    # ------------------------------------------------------------ ingestion

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        text = load_document(filepath)
        if not text.strip():
            raise ValueError(f"no text extracted from {filename}")
        chunks = self.ctx.splitter().split(text)
        docs = [Document(content=c, metadata={"source": filename})
                for c in chunks]
        self.ctx.store(COLLECTION).add(
            docs, self.ctx.embedder.embed_documents([d.content for d in docs]))

    # -------------------------------------------------------------- routing

    def route(self, query: str) -> Dict[str, Any]:
        reply = "".join(self.ctx.llm.chat(
            [{"role": "user", "content": ROUTE_PROMPT.format(query=query)}],
            max_tokens=128, temperature=0.0))
        decision = parse_route(reply)
        logger.info("routed %r -> %s", query[:60], decision["sources"])
        return decision

    def _gather(self, query: str, decision: Dict[str, Any]) -> List[str]:
        """Run the chosen branches; each contributes source-tagged snippets
        (the workflow's NodeCollectEvent join)."""
        search_q = decision["rewritten"] or query
        parts: List[str] = []
        if "kb" in decision["sources"]:
            hits = self.ctx.store(COLLECTION).search(
                self.ctx.embedder.embed_queries([search_q])[0],
                top_k=self.ctx.config.retriever.top_k,
                score_threshold=self.ctx.config.retriever.score_threshold)
            parts += [f"[kb] {d.content}" for d, _ in hits]
        if "web" in decision["sources"]:
            for r in self.web.search(search_q):
                snippet = r.get("snippet") or r.get("content", "")
                url = r.get("url", "")
                parts.append(f"[web] {snippet} ({url})".strip())
        return parts

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        yield from self.ctx.llm.chat(
            list(chat_history) + [{"role": "user", "content": query}],
            **_sampling(llm_settings))

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        decision = self.route(query)
        if decision["sources"] == ["direct"]:
            yield from self.llm_chain(query, chat_history, **llm_settings)
            return
        parts = self._gather(query, decision)
        context = trim_context(parts, self.ctx.embedder.tokenizer, 1500)
        guardrails.record_context(context)
        messages = ([{"role": "system",
                      "content": SYNTH_PROMPT.format(
                          context=context or "(no sources returned results)")}]
                    + list(chat_history)
                    + [{"role": "user", "content": query}])
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    # ------------------------------------------------------------ documents

    def document_search(self, query: str, top_k: int = 4) -> List[Dict[str, Any]]:
        hits = self.ctx.store(COLLECTION).search(
            self.ctx.embedder.embed_queries([query])[0], top_k=top_k)
        return [{"content": d.content, "score": float(score),
                 "source": str(d.metadata.get("source", ""))}
                for d, score in hits]

    def get_documents(self) -> List[str]:
        return self.ctx.store(COLLECTION).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> None:
        self.ctx.store(COLLECTION).delete_by_source(filenames)

