"""knowledge_graph_rag — triple extraction → NetworkX graph → graph-augmented RAG.

Behavioral parity with the reference's knowledge-graph community app
(ref: community/knowledge_graph_rag/backend/utils/preprocessor.py:52-80 —
LLM triple extraction with a fixed relation-verb set and list-of-tuples
output; utils/lc_graph.py process_documents — split → extract per chunk →
graph; routers/chat.py — GraphQAChain over a NetworkxEntityGraph loaded
from graphml, answering only from graph context). cuGraph acceleration is
replaced by plain NetworkX per SURVEY §2.5 (graph ops are not the TPU's
job); embedding/generation run on the in-proc TPU engines.

Design differences (documented, deliberate):
  * entity linking for queries is lexical-first (graph nodes found in the
    query string) with an LLM fallback, instead of always burning an LLM
    call (ref chat.py extracts entities with a second chain);
  * ingest also indexes chunks in the dense store, so `rag_chain` can fuse
    graph triples with vector context (the app keeps these separate pages);
  * the graph persists as graphml next to the store, matching the
    reference's KG_GRAPHML_PATH contract.
"""

from __future__ import annotations

import ast
import logging
import os
import re
from typing import Any, Dict, Iterator, List, Sequence, Tuple

from generativeaiexamples_tpu.chains.basic_rag import _sampling, trim_context
from generativeaiexamples_tpu.server import guardrails
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.chains.loaders import load_document
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

COLLECTION = "knowledge_graph_rag"

# ref preprocessor.py:68 — the fixed relation-verb vocabulary the extractor
# is constrained to (keeps the graph queryable)
RELATIONS = ("Has", "Announce", "Operate_In", "Introduce", "Produce",
             "Control", "Participates_In", "Impact", "Positive_Impact_On",
             "Negative_Impact_On", "Relate_To", "Is_Member_Of", "Invests_In",
             "Raise", "Decrease")

EXTRACT_PROMPT = """\
You are a knowledge-graph builder. Extract entity triples from the text.
The relationship 'r' between entities must be one of: {relations}.
Output ONLY a python list of tuples, each ['h', 'type', 'r', 'o', 'type']
where every element is a string and 'r' is from the set above. Example:
[('Nvidia', 'Company', 'Introduce', 'H100', 'Product')]

Text:
{text}
"""

ANSWER_PROMPT = """\
You are a helpful AI assistant. Reply to questions only based on the context
you are provided. If something is out of context, politely decline to answer.

Knowledge-graph facts:
{triples}

Supporting passages:
{context}
"""


def parse_triples(text: str) -> List[Tuple[str, str, str, str, str]]:
    """Parse the extractor's list-of-tuples output defensively: the LLM may
    wrap it in prose or emit partially malformed entries — salvage every
    well-formed 5-tuple whose relation is in the vocabulary, drop the rest
    (ref preprocessor.py:30-49 does the same filtering loop)."""
    match = re.search(r"\[.*\]", text, re.DOTALL)
    if not match:
        return []
    try:
        items = ast.literal_eval(match.group())
    except (ValueError, SyntaxError):
        return []
    out = []
    if not isinstance(items, (list, tuple)):
        return []
    for item in items:
        if (isinstance(item, (list, tuple)) and len(item) == 5
                and all(isinstance(e, str) for e in item)
                and item[2] in RELATIONS):
            out.append(tuple(e.strip() for e in item))
    return out


@register_example("knowledge_graph_rag")
class KnowledgeGraphRAG(BaseExample):
    """Graph-augmented RAG over an LLM-extracted entity graph."""

    def __init__(self, context: ChainContext = None,
                 graph_path: str = "") -> None:
        import networkx as nx

        self.ctx = context or get_context()
        self._nx = nx
        self.graph_path = graph_path or os.environ.get(
            "KG_GRAPHML_PATH", "")
        # MultiDiGraph: the same (h, o) pair can carry several relations
        # from several documents — a plain DiGraph would overwrite the
        # first fact (and its source attribution) with the second
        if self.graph_path and os.path.exists(self.graph_path):
            self.graph = nx.read_graphml(self.graph_path,
                                         force_multigraph=True)
            logger.info("loaded knowledge graph: %d nodes / %d edges",
                        self.graph.number_of_nodes(),
                        self.graph.number_of_edges())
        else:
            self.graph = nx.MultiDiGraph()

    # ------------------------------------------------------------ ingestion

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        """Split → extract triples per chunk (LLM) → merge into the graph;
        chunks also land in the dense store for hybrid answers."""
        text = load_document(filepath)
        if not text.strip():
            raise ValueError(f"no text extracted from {filename}")
        chunks = self.ctx.splitter().split(text)
        n_triples = 0
        for chunk in chunks:
            prompt = EXTRACT_PROMPT.format(
                relations=", ".join(RELATIONS), text=chunk)
            reply = "".join(self.ctx.llm.chat(
                [{"role": "user", "content": prompt}],
                max_tokens=512, temperature=0.0))
            for h, h_type, rel, o, o_type in parse_triples(reply):
                self.graph.add_node(h, type=h_type)
                self.graph.add_node(o, type=o_type)
                self.graph.add_edge(h, o, relation=rel, source=filename)
                n_triples += 1
        docs = [Document(content=c, metadata={"source": filename})
                for c in chunks]
        embeddings = self.ctx.embedder.embed_documents([d.content for d in docs])
        self.ctx.store(COLLECTION).add(docs, embeddings)
        if self.graph_path:
            self._nx.write_graphml(self.graph, self.graph_path)
        logger.info("ingested %s: %d chunks, %d triples (graph now %d/%d)",
                    filename, len(chunks), n_triples,
                    self.graph.number_of_nodes(), self.graph.number_of_edges())

    # ------------------------------------------------------------ retrieval

    def _query_entities(self, query: str) -> List[str]:
        """Lexical-first entity linking: graph nodes appearing in the query
        (case-insensitive); LLM fallback when nothing matches."""
        q = query.lower()
        found = [n for n in self.graph.nodes if str(n).lower() in q]
        if found:
            return found
        if self.graph.number_of_nodes() == 0:
            return []
        reply = "".join(self.ctx.llm.chat(
            [{"role": "user", "content":
              "List the named entities in this question as a comma-"
              f"separated line, nothing else: {query}"}],
            max_tokens=64, temperature=0.0))
        cands = [c.strip() for c in reply.split(",") if c.strip()]
        lower = {str(n).lower(): n for n in self.graph.nodes}
        return [lower[c.lower()] for c in cands if c.lower() in lower]

    def graph_context(self, query: str, hops: int = 1,
                      limit: int = 40) -> List[str]:
        """Triples within ``hops`` of the query's entities, rendered as
        'h -[r]-> o' lines (the GraphQAChain neighborhood semantics)."""
        entities = self._query_entities(query)
        if not entities:
            return []
        sub = set(entities)
        frontier = set(entities)
        for _ in range(hops):
            nxt = set()
            for n in frontier:
                nxt |= set(self.graph.successors(n))
                nxt |= set(self.graph.predecessors(n))
            sub |= nxt
            frontier = nxt
        lines = []
        for h, o, data in self.graph.edges(sub, data=True):
            if h in sub and o in sub:
                lines.append(f"{h} -[{data.get('relation', 'Relate_To')}]-> {o}")
                if len(lines) >= limit:
                    break
        return lines

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        messages = (list(chat_history)
                    + [{"role": "user", "content": query}])
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        triples = self.graph_context(query)
        top_k = self.ctx.config.retriever.top_k
        hits = self.ctx.store(COLLECTION).search(
            self.ctx.embedder.embed_queries([query])[0], top_k=top_k,
            score_threshold=self.ctx.config.retriever.score_threshold)
        context = trim_context([d.content for d, _ in hits],
                               self.ctx.embedder.tokenizer, 1500)
        guardrails.record_context(context)
        system = ANSWER_PROMPT.format(
            triples="\n".join(triples) if triples else "(none found)",
            context=context or "(no passages retrieved)")
        messages = ([{"role": "system", "content": system}]
                    + list(chat_history) + [{"role": "user", "content": query}])
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    # ------------------------------------------------------------ documents

    def document_search(self, query: str, top_k: int = 4) -> List[Dict[str, Any]]:
        hits = self.ctx.store(COLLECTION).search(
            self.ctx.embedder.embed_queries([query])[0], top_k=top_k)
        return [{"content": d.content, "score": float(score),
                 "source": str(d.metadata.get("source", ""))}
                for d, score in hits]

    def get_documents(self) -> List[str]:
        return self.ctx.store(COLLECTION).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> None:
        self.ctx.store(COLLECTION).delete_by_source(filenames)
        # drop edges extracted from those files; prune now-isolated nodes
        doomed = [(h, o, k) for h, o, k, d in
                  self.graph.edges(keys=True, data=True)
                  if d.get("source") in set(filenames)]
        self.graph.remove_edges_from(doomed)
        self.graph.remove_nodes_from(
            [n for n in list(self.graph.nodes) if self.graph.degree(n) == 0])
        if self.graph_path:
            self._nx.write_graphml(self.graph, self.graph_path)

