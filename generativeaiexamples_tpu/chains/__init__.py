"""Example chains — the pluggable RAG pipelines (ref: RAG/examples/).

Each module registers a `BaseExample` with the server registry:

  basic_rag            ingest→split→embed→store; retrieve→prompt→stream
                       (ref basic_rag/langchain/chains.py)
  multi_turn_rag       conversation memory + retrieve-40→rerank-4 funnel
                       (ref advanced_rag/multi_turn_rag/chains.py)
  query_decomposition  recursive sub-question agent with search+math tools
                       (ref advanced_rag/query_decomposition_rag/chains.py)
  structured_data      CSV Q&A over pandas (ref advanced_rag/structured_data_rag)
  multimodal           PDF/PPTX/image ingestion + captioning
                       (ref advanced_rag/multimodal_rag)
  agentic_rag          self-corrective graph: grade→rewrite→regenerate
                       (ref notebooks/langchain/agentic_rag_with_nemo_retriever_nim.ipynb)
  knowledge_graph_rag  LLM triple extraction → NetworkX graph → graph+dense RAG
                       (ref community/knowledge_graph_rag)
  text_to_sql          Vanna-style retrieval-augmented SQL over sqlite,
                       read-only authorizer (ref asset_lifecycle vanna_util.py)
  router_rag           route queries across KB / web seam / direct LLM
                       (ref community/routing-multisource-rag/workflow.py)
  bash_agent           allowlisted bash computer-use agent loop
                       (ref nemotron/LLM/bash_computer_use_agent)

All chains share `ChainContext` (engine + encoders + stores) so one process
serves any example — the compose-file indirection of the reference collapses
into in-proc wiring.
"""

from generativeaiexamples_tpu.chains.context import ChainContext, get_context  # noqa: F401

# Shared retrieval-failure message (ref chains.py "No response generated…"
# strings, identical across the reference examples).
NO_CONTEXT_MSG = ("No response generated from LLM, make sure your query is "
                  "relevant to the ingested document.")
