"""query_decomposition_rag — recursive task-decomposition agent.

Behavioral parity with the reference agent (ref: RAG/examples/advanced_rag/
query_decomposition_rag/chains.py): a tool-selector LLM call emits JSON
{"Tool_Request", "Generated Sub Questions"}; Search retrieves + extracts a
concise answer per sub-question into a ledger (chains.py:307-318), Math
extracts two variables + an operation as JSON and computes the result
(chains.py:320-345); the loop stops on Tool_Request "Nil", empty/repeated
sub-questions, or trace depth > 3 (CustomOutputParser.parse,
chains.py:120-146); the accumulated ledger becomes the final-answer prompt
(run_agent, chains.py:257-274).

Differences by design: the math step evaluates with an explicit operator
table instead of `eval` (the reference eval's LLM output — chains.py:333),
and the agent is a plain loop rather than a LangChain AgentExecutor.
"""

from __future__ import annotations

import json
import logging
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence

from generativeaiexamples_tpu.chains.basic_rag import _sampling
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.chains.loaders import load_document
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server import guardrails
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

from generativeaiexamples_tpu.chains import NO_CONTEXT_MSG

COLLECTION = "query_decomposition"
MAX_TRACE = 3  # ref chains.py:133 — "self.ledger.trace > 3"

_OPS = {
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b, "/": lambda a, b: a / b,
    "=": lambda a, b: a == b, ">": lambda a, b: a > b,
    "<": lambda a, b: a < b, ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}


def extract_json(text: str) -> Optional[Dict[str, Any]]:
    """First balanced JSON OBJECT in `text` (models wrap JSON in prose).

    Delegates to the serving layer's string-aware scanner
    (engine/tools.py:extract_json_value — one scanner to keep
    bug-compatible, there and here), skipping over non-object values:
    chains expect a dict."""
    from generativeaiexamples_tpu.engine.tools import extract_json_value

    def first_dict(value):
        if isinstance(value, dict):
            return value
        if isinstance(value, list):   # models wrap the object in an array
            for v in value:
                d = first_dict(v)
                if d is not None:
                    return d
        return None

    pos = 0
    while pos < len(text):
        found = extract_json_value(text[pos:])
        if found is None:
            return None
        value, (_, end) = found
        d = first_dict(value)
        if d is not None:
            return d
        pos += end
    return None


def _scalar(value: Any) -> float:
    """LLMs return variables as numbers, strings, or 1-element lists."""
    if isinstance(value, (list, tuple)):
        value = value[0]
    if isinstance(value, str):
        m = re.search(r"-?\d+(?:\.\d+)?", value.replace(",", ""))
        if not m:
            raise ValueError(f"no number in {value!r}")
        value = m.group(0)
    return float(value)


@dataclass
class Ledger:
    """State of the recursive decomposition (ref chains.py:72-77)."""
    question_trace: List[str] = field(default_factory=list)
    answer_trace: List[str] = field(default_factory=list)
    trace: int = 0
    done: bool = False

    def context(self) -> str:
        """ref fetch_context, chains.py:81-89."""
        lines = []
        for q, a in zip(self.question_trace, self.answer_trace):
            lines.append(f"Sub-Question: {q}\nSub-Answer: {a}")
        return "\n".join(lines)


@register_example("query_decomposition_rag")
class QueryDecompositionRAG(BaseExample):
    def __init__(self, context: ChainContext = None) -> None:
        self.ctx = context or get_context()

    # ------------------------------------------------------------ ingestion

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        if not filename.lower().endswith((".txt", ".pdf", ".md")):
            raise ValueError(
                f"{filename} is not a valid Text, PDF or Markdown file")
        text = load_document(filepath)
        if not text.strip():
            raise ValueError(f"no text extracted from {filename}")
        chunks = self.ctx.splitter().split(text)
        docs = [Document(content=c, metadata={"source": filename})
                for c in chunks]
        embeddings = self.ctx.embedder.embed_documents([d.content for d in docs])
        self.ctx.store(COLLECTION).add(docs, embeddings)

    # ----------------------------------------------------------- LLM helpers

    def _complete(self, prompt: str, **settings: Any) -> str:
        """Non-streaming completion used by agent-internal calls."""
        s = _sampling(settings)
        s["max_tokens"] = min(s["max_tokens"], 256)
        return "".join(self.ctx.llm.chat(
            [{"role": "user", "content": prompt}], **s))

    # ------------------------------------------------------------ the tools

    def _retrieve(self, query: str) -> List[str]:
        """ref retriever(), chains.py:276-291 — no threshold for this agent."""
        qvec = self.ctx.embedder.embed_queries([query])[0]
        hits = self.ctx.store(COLLECTION).search(
            qvec, top_k=self.ctx.config.retriever.top_k, score_threshold=0.0)
        return [d.content for d, _ in hits]

    def _extract_answer(self, chunks: List[str], question: str,
                        **settings: Any) -> str:
        """ref extract_answer, chains.py:293-305."""
        parts = [self.ctx.prompts["answer_extraction_prompt"],
                 f"\nQuestion: {question}\n"]
        for idx, chunk in enumerate(chunks):
            parts.append(f"Passage {idx + 1}:\n{chunk}\n")
        return self._complete("\n".join(parts), **settings).strip()

    def _search(self, ledger: Ledger, sub_questions: List[str],
                **settings: Any) -> None:
        """ref search(), chains.py:307-318."""
        for sub_q in sub_questions:
            chunks = self._retrieve(sub_q)
            ledger.question_trace.append(sub_q)
            ledger.answer_trace.append(
                self._extract_answer(chunks, sub_q, **settings))

    def _math(self, ledger: Ledger, sub_questions: List[str],
              **settings: Any) -> None:
        """ref math(), chains.py:320-345 — JSON variable extraction with an
        LLM fallback; computation via operator table, never eval."""
        question = sub_questions[0]
        answer: str
        try:
            prompt = (self.ctx.prompts["math_tool_prompt"].format(
                context=ledger.context(), question=question))
            parsed = extract_json(self._complete(prompt, **settings))
            a = _scalar(parsed["variable1"])
            b = _scalar(parsed["variable2"])
            op = parsed["operation"]
            if isinstance(op, (list, tuple)):
                op = op[0]
            answer = f"{a}{op}{b}={_OPS[op](a, b)}"
        except Exception as exc:  # fall back to a concise LLM answer
            logger.info("math JSON path failed (%s); falling back", exc)
            prompt = (f"Solve this mathematical question:\n"
                      f"Question: {question}\n"
                      f"Context:\n{ledger.context()}\n"
                      f"Be concise and only return the answer.")
            answer = self._complete(prompt, **settings).strip()
        ledger.question_trace.append(question)
        ledger.answer_trace.append(answer)
        ledger.done = True

    # ---------------------------------------------------------- agent loop

    def _run_agent(self, question: str, **settings: Any) -> str:
        """Recursive decomposition; returns the final-answer prompt built
        from the ledger (ref run_agent, chains.py:257-274)."""
        ledger = Ledger()
        while not ledger.done:
            prompt = self.ctx.prompts["tool_selector_prompt"].format(
                context=ledger.context(), question=question)
            raw = self._complete(prompt, **settings)
            logger.info("tool selector: %s", raw.strip()[:400])
            state = extract_json(raw)
            if state is None:
                logger.warning("tool selector returned no JSON; finishing")
                break
            raw_subs = state.get("Generated Sub Questions", [])
            if isinstance(raw_subs, str):  # schema deviation: bare string
                raw_subs = [raw_subs]
            elif not isinstance(raw_subs, (list, tuple)):
                raw_subs = [str(raw_subs)]
            sub_qs = [str(q) for q in raw_subs if str(q).strip()]
            tool = str(state.get("Tool_Request", "Nil")).strip()
            # stop conditions (ref CustomOutputParser.parse, chains.py:127-137)
            if (not sub_qs or sub_qs[0] == "Nil" or tool == "Nil"
                    or ledger.trace > MAX_TRACE
                    or sub_qs[0] in ledger.question_trace):
                break
            if tool == "Search":
                ledger.trace += 1
                self._search(ledger, sub_qs, **settings)
            elif tool == "Math":
                self._math(ledger, sub_qs, **settings)
            else:
                logger.warning("invalid tool %r; finishing", tool)
                break

        parts = [f"Question: {question}\n", "Sub Questions and Answers"]
        qa_lines = []
        for q, a in zip(ledger.question_trace, ledger.answer_trace):
            qa_lines.append(f"Sub Question: {q}")
            qa_lines.append(f"Sub Answer: {a}")
        parts.extend(qa_lines)
        parts.append("\nFinal Answer: ")
        # the final answer is generated from this sub-Q/A evidence — the
        # fact-check rail must judge against it, not a fresh retrieval
        guardrails.record_context("\n".join(qa_lines))
        return "\n".join(parts)

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        messages = [{"role": "system",
                     "content": self.ctx.prompts["chat_template"]},
                    {"role": "user", "content": f"\n\nQuestion: {query}\n"}]
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        try:
            final_prompt = self._run_agent(query, **llm_settings)
        except ValueError as exc:
            logger.warning("agent failed: %s", exc)
            yield "I can't find an answer for that."
            return
        if "Sub Question:" not in final_prompt:
            yield NO_CONTEXT_MSG
            return
        yield from self.ctx.llm.chat(
            [{"role": "user", "content": final_prompt}],
            **_sampling(llm_settings))

    # ------------------------------------------------------------ documents

    def document_search(self, query: str, num_docs: int = 4) -> List[Dict[str, Any]]:
        qvec = self.ctx.embedder.embed_queries([query])[0]
        hits = self.ctx.store(COLLECTION).search(
            qvec, top_k=num_docs, score_threshold=0.0)
        return [{"source": str(d.metadata.get("source", "")),
                 "content": d.content, "score": score}
                for d, score in hits]

    def get_documents(self) -> List[str]:
        return self.ctx.store(COLLECTION).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> bool:
        return self.ctx.store(COLLECTION).delete_by_source(filenames) > 0
