"""Event-driven agent loop — queue-triggered analysis with bounded concurrency.

Behavioral parity with the reference's event-driven RAG CVE pipeline
(ref: community/event-driven-rag-cve-analysis — a Morpheus/Kafka consumer
triggers an LLM agent per incoming CVE event: look up the knowledge base,
run the analysis chain, publish a structured verdict; failures are retried
and surfaced, not dropped). The Kafka/Morpheus runtime is replaced by an
asyncio consumer over pluggable async event sources — the same seam
pattern as retrieval/streaming_ingest.py: a Kafka source is a ~10-line
async generator against the `Event` contract.

Mechanics the reference gets from its streaming engine, kept here:
  * bounded concurrency (a flood of events cannot stampede the TPU);
  * per-event retry with capped attempts and FULL-JITTER exponential
    backoff (server/resilience.py — the shared implementation; the old
    linear ``retry_delay_s * attempt`` sleep retried a correlated burst
    of failures in lockstep), then a dead-letter list — an event is
    either answered, or visibly failed, never lost. Dead letters count
    into ``event_agent_dead_letter_total`` and the most recent ride the
    process-wide :data:`DEAD_LETTERS` ring, served at
    ``GET /debug/deadletter`` (server/common.py) — a poisoned topic is
    an operator page, not a log archaeology project;
  * results stream to a sink callback as they finish (publish side).
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import threading
import time
from collections import deque
from typing import (Any, AsyncIterator, Callable, Dict, List, Optional,
                    Sequence)

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.server.resilience import full_jitter_backoff

logger = logging.getLogger(__name__)

# process-wide dead-letter ring (newest last): every EventDrivenAgent in
# the process appends here so /debug/deadletter shows poisoned events
# without a handle on the agent instance. Bounded — an unbounded poison
# topic must not become an unbounded memory leak.
DEAD_LETTERS: deque = deque(maxlen=256)
_DEAD_LOCK = threading.Lock()


def record_dead_letter(event: "Event", error: str, attempts: int) -> None:
    with _DEAD_LOCK:
        DEAD_LETTERS.append({"ts_unix": round(time.time(), 3),
                             "key": event.key, "error": error,
                             "attempts": attempts})
    REGISTRY.counter("event_agent_dead_letter_total").inc()


def dead_letter_payload() -> Dict[str, Any]:
    """The ``GET /debug/deadletter`` body (newest first)."""
    with _DEAD_LOCK:
        items = list(DEAD_LETTERS)[::-1]
    return {"total": REGISTRY.counter("event_agent_dead_letter_total").value,
            "ring_capacity": DEAD_LETTERS.maxlen,
            "dead_letters": items}


@dataclasses.dataclass
class Event:
    """One triggering event (e.g. a CVE advisory landing on a topic)."""
    key: str
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)
    attempt: int = 0


@dataclasses.dataclass
class EventResult:
    key: str
    output: str = ""
    ok: bool = True
    error: str = ""
    attempts: int = 1
    latency_s: float = 0.0


async def list_source(events: Sequence[Event]) -> AsyncIterator[Event]:
    """In-tree source: a finite batch (tests, backfills)."""
    for e in events:
        yield e


async def jsonl_event_source(path: str, key_field: str = "id"
                             ) -> AsyncIterator[Event]:
    """Events from a JSONL feed (the file-tap equivalent of a topic)."""
    def read():
        with open(path, "r", encoding="utf-8") as fh:
            return [json.loads(line) for line in fh if line.strip()]

    for row in await asyncio.to_thread(read):
        yield Event(key=str(row.get(key_field, "")), payload=row)


class EventDrivenAgent:
    """Consumes events, runs ``handler`` per event under a concurrency cap.

    handler: Callable[[Event], str] — typically a closure over a chain or
    ToolAgent; runs on a worker thread (chains block on device work)."""

    def __init__(self, handler: Callable[[Event], str],
                 result_sink: Optional[Callable[[EventResult], None]] = None,
                 max_concurrency: int = 4, max_retries: int = 2,
                 retry_delay_s: float = 0.5,
                 retry_cap_s: float = 30.0) -> None:
        self.handler = handler
        self.result_sink = result_sink
        self.max_concurrency = max_concurrency
        self.max_retries = max_retries
        # base of the shared full-jitter exponential backoff (retry n
        # sleeps uniform in [0, min(retry_cap_s, retry_delay_s * 2^(n-1))])
        self.retry_delay_s = retry_delay_s
        self.retry_cap_s = retry_cap_s
        self.results: List[EventResult] = []
        self.dead_letter: List[Event] = []

    async def _process(self, event: Event,
                       sem: asyncio.Semaphore) -> None:
        async with sem:
            t0 = time.perf_counter()
            attempt = 0
            while True:
                attempt += 1
                try:
                    output = await asyncio.to_thread(self.handler, event)
                    result = EventResult(
                        key=event.key, output=output, attempts=attempt,
                        latency_s=time.perf_counter() - t0)
                    break
                except Exception as exc:
                    logger.warning("event %s attempt %d failed: %s",
                                   event.key, attempt, exc)
                    if attempt > self.max_retries:
                        result = EventResult(
                            key=event.key, ok=False, error=str(exc),
                            attempts=attempt,
                            latency_s=time.perf_counter() - t0)
                        self.dead_letter.append(
                            dataclasses.replace(event, attempt=attempt))
                        record_dead_letter(event, str(exc), attempt)
                        break
                    # jittered exponential backoff (shared helper): a
                    # correlated failure burst (engine restart, dead
                    # retriever) retries decorrelated instead of in
                    # lockstep waves
                    await asyncio.sleep(full_jitter_backoff(
                        attempt, base_s=self.retry_delay_s,
                        cap_s=self.retry_cap_s))
        self.results.append(result)
        if self.result_sink is not None:
            try:
                self.result_sink(result)
            except Exception:
                logger.exception("result sink failed for %s", event.key)

    async def run(self, source: AsyncIterator[Event]) -> Dict[str, int]:
        sem = asyncio.Semaphore(self.max_concurrency)
        tasks = []
        async for event in source:
            tasks.append(asyncio.ensure_future(self._process(event, sem)))
        if tasks:
            await asyncio.gather(*tasks)
        ok = sum(1 for r in self.results if r.ok)
        return {"processed": len(self.results), "succeeded": ok,
                "failed": len(self.results) - ok,
                "dead_letter": len(self.dead_letter)}

    def run_sync(self, source: AsyncIterator[Event]) -> Dict[str, int]:
        return asyncio.run(self.run(source))


# ------------------------------------------------------- concrete handler

CVE_TRIAGE_PROMPT = """\
You are a security analyst. A new advisory arrived:

{advisory}

Relevant internal context (software inventory, prior notes):
{context}

Assess whether our deployment is affected. Respond with ONLY a JSON object:
{{"cve": "<id>", "affected": true|false, "severity": "low|medium|high|critical",
"justification": "<one paragraph>"}}"""


def make_cve_triage_handler(ctx, collection: str = "default",
                            top_k: int = 4, **sampling) -> Callable[[Event], str]:
    """The reference pipeline's analysis step as a handler: retrieve
    deployment context for the advisory, ask the LLM for a structured
    verdict (ref: event-driven-rag-cve-analysis's LLM agent stage)."""
    from generativeaiexamples_tpu.engine.tools import extract_json_value

    def handler(event: Event) -> str:
        advisory = json.dumps(event.payload)
        query = f"{event.key} {event.payload.get('summary', '')}"
        qvec = ctx.embedder.embed_queries([query])[0]
        hits = ctx.store(collection).search(qvec, top_k=top_k)
        context = "\n\n".join(d.content for d, _ in hits) or "(none)"
        prompt = CVE_TRIAGE_PROMPT.format(advisory=advisory, context=context)
        text = "".join(ctx.llm.chat(
            [{"role": "user", "content": prompt}], **sampling))
        found = extract_json_value(text)
        if found is None:
            raise ValueError(f"no JSON verdict in analysis for {event.key}")
        verdict = found[0]
        if not isinstance(verdict, dict) or "affected" not in verdict:
            raise ValueError(f"malformed verdict for {event.key}")
        verdict.setdefault("cve", event.key)
        return json.dumps(verdict)

    return handler
