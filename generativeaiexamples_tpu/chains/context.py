"""Shared runtime context for chains: LLM, encoders, stores, splitter, prompts.

The in-proc equivalent of the reference's cached client factories hub
(ref: utils.py get_llm:366 / get_embedding_model:407 / get_ranking_model:448 /
get_text_splitter:474 / create_vectorstore_langchain:288): one `ChainContext`
owns the TPU engines and hands chains their dependencies, so every example
runs in a single process with no HTTP hops between pipeline stages.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional

from generativeaiexamples_tpu.core.config import AppConfig, get_config
from generativeaiexamples_tpu.core.prompts import get_prompts
from generativeaiexamples_tpu.encoders.embedder import Embedder
from generativeaiexamples_tpu.encoders.reranker import Reranker
from generativeaiexamples_tpu.retrieval.store import VectorStore
from generativeaiexamples_tpu.retrieval.text_splitter import TokenTextSplitter


@dataclass
class ChainContext:
    config: AppConfig
    llm: object
    embedder: Embedder
    reranker: Optional[Reranker] = None
    stores: Dict[str, VectorStore] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def prompts(self) -> Dict[str, str]:
        return get_prompts()

    def store(self, collection: str = "default") -> VectorStore:
        """Named collections (ref: COLLECTION_NAME env per example,
        docker-compose.yaml:24-27). The backend is config-dispatched —
        in-proc device-resident by default, Milvus/pgvector adapters for
        deployments running those services (ref utils.py:220-332)."""
        from generativeaiexamples_tpu.retrieval.adapters import make_store

        with self._lock:
            if collection not in self.stores:
                self.stores[collection] = make_store(
                    dim=self.embedder.dim, config=self.config.vector_store,
                    name=collection)
            return self.stores[collection]

    def splitter(self) -> TokenTextSplitter:
        ts = self.config.text_splitter
        return TokenTextSplitter(chunk_size=ts.chunk_size,
                                 chunk_overlap=ts.chunk_overlap)


_context: Optional[ChainContext] = None
_context_lock = threading.Lock()


def get_context(scheduler=None) -> ChainContext:
    """Process-wide context; builds engines on first use."""
    global _context
    with _context_lock:
        if _context is None:
            from generativeaiexamples_tpu.chains.llm_client import get_llm

            config = get_config()
            # process-wide encoders micro-batch across requests: every
            # chain's embed/rerank call rides shared TPU dispatches
            # (encoders/microbatch.py; windows in core/config.py)
            _context = ChainContext(
                config=config,
                llm=get_llm(scheduler),
                embedder=Embedder(
                    micro_window_s=config.embeddings.microbatch_window_ms
                    / 1e3),
                reranker=Reranker(
                    micro_window_s=config.ranking.microbatch_window_ms / 1e3),
            )
        return _context


def set_context(context: Optional[ChainContext]) -> None:
    """Test hook / server wiring."""
    global _context
    with _context_lock:
        _context = context
