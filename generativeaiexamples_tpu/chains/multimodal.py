"""multimodal_rag — PDF/PPTX/PNG ingestion with image description.

Behavioral parity with the reference example (ref: RAG/examples/
advanced_rag/multimodal_rag/chains.py: ingest accepts only pdf/pptx/png
(chains.py:69-75); images are described by a VLM before embedding
(vectorstore/vectorstore_updater.py:69 + llm/llm_client.py
multimodal_invoke:48); retrieval then augments the prompt with the text
and image descriptions (chains.py rag_chain)).

The VLM is a seam: `ImageDescriber`. Four backends, picked by
`get_describer` in priority order: a remote OpenAI-compatible VLM endpoint
(APP_VLM_SERVER_URL), the in-tree LLaVA-architecture VLM generating
captions on-device (models/vlm.py, when APP_VLM_CHECKPOINT_DIR points at a
HF Llava checkpoint), the CLIP vision tower's zero-shot captioner
(encoders/vision.ClipCaptioner, when APP_VISION_CHECKPOINT_DIR supplies
real weights or APP_VISION_CAPTIONER=clip), and a deterministic
structural-stats stub so the pipeline is fully self-contained.
"""

from __future__ import annotations

import base64
import logging
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

from generativeaiexamples_tpu.chains.basic_rag import _sampling, trim_context
from generativeaiexamples_tpu.server import guardrails
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.chains.multimodal_parsers import (
    Element, image_summary, parse_multimodal)
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

from generativeaiexamples_tpu.chains import NO_CONTEXT_MSG

COLLECTION = "multimodal"

# type: takes (image_bytes, metadata) -> caption
ImageDescriber = Callable[[bytes, Dict[str, str]], str]


def stub_describer(image_bytes: bytes, metadata: Dict[str, str]) -> str:
    """Deterministic caption from image structure (no model)."""
    summary = image_summary(image_bytes) or "undecodable image"
    src = metadata.get("source", "unknown")
    return f"Image from {src}: {summary}"


def remote_vlm_describer(base_url: str, model: str) -> ImageDescriber:
    """Caption via an OpenAI-compatible VLM endpoint (the reference's
    NeuVA/VLM path, ref llm/llm_client.py multimodal_invoke:48)."""
    def describe(image_bytes: bytes, metadata: Dict[str, str]) -> str:
        import httpx

        b64 = base64.b64encode(image_bytes).decode("ascii")
        payload = {
            "model": model,
            "messages": [{"role": "user", "content": [
                {"type": "text",
                 "text": "Describe this image concisely, including any "
                         "chart or graph content."},
                {"type": "image_url",
                 "image_url": {"url": f"data:image/png;base64,{b64}"}},
            ]}],
            "max_tokens": 160,
        }
        resp = httpx.post(f"{base_url.rstrip('/')}/v1/chat/completions",
                          json=payload, timeout=60.0)
        resp.raise_for_status()
        return resp.json()["choices"][0]["message"]["content"]
    return describe


def clip_describer() -> ImageDescriber:
    """Caption with the in-tree CLIP tower (encoders/vision.ClipCaptioner):
    zero-shot caption-bank scoring in the joint space + structural stats."""
    from generativeaiexamples_tpu.encoders.vision import ClipCaptioner

    captioner = ClipCaptioner()
    return captioner.describe


def local_vlm_describer(checkpoint_dir: str) -> ImageDescriber:
    """Caption with the in-tree LLaVA-architecture VLM (models/vlm.py):
    a HF Llava checkpoint directory (safetensors/bin + tokenizer.json)
    generates captions fully on-device — the strongest in-tree backend
    behind the multimodal_invoke seam."""
    from generativeaiexamples_tpu.engine.tokenizer import get_tokenizer
    from generativeaiexamples_tpu.models import vlm as vlm_lib

    cfg, params = vlm_lib.load_checkpoint(checkpoint_dir)
    tok = get_tokenizer(checkpoint_dir)

    def describe(image_bytes: bytes, metadata: Dict[str, str]) -> str:
        from generativeaiexamples_tpu.encoders.vision import (
            _MEAN, _STD, _decode_image)

        arr = _decode_image(image_bytes, cfg.clip.image_size)
        if arr is None:
            return stub_describer(image_bytes, metadata)
        # the tower was trained behind CLIPImageProcessor normalization —
        # raw [0,1] pixels are ~2σ out of distribution
        arr = (arr - _MEAN) / _STD
        prompt = vlm_lib.build_prompt(
            cfg, tok.encode("Describe this image concisely, including any "
                            "chart or graph content.\n"),
            bos_id=tok.bos_id)
        import jax.numpy as jnp

        out = vlm_lib.generate(params, cfg, jnp.asarray(arr[None]),
                               prompt, max_tokens=96, eos_id=tok.eos_id)
        return tok.decode(out).strip() or stub_describer(image_bytes,
                                                         metadata)
    return describe


def get_describer() -> ImageDescriber:
    """Priority: served VLM endpoint > in-tree LLaVA VLM (when a Llava
    checkpoint dir is configured) > in-tree CLIP tower (real CLIP
    checkpoint or explicit opt-in) > structural stub. Random-weight models
    would caption noise, so each model backend requires its checkpoint."""
    url = os.environ.get("APP_VLM_SERVER_URL", "")
    if url:
        model = os.environ.get("APP_VLM_MODEL_NAME", "vlm")
        return remote_vlm_describer(url, model)
    vlm_dir = os.environ.get("APP_VLM_CHECKPOINT_DIR", "")
    if vlm_dir:
        return local_vlm_describer(vlm_dir)
    if (os.environ.get("APP_VISION_CHECKPOINT_DIR")
            or os.environ.get("APP_VISION_CAPTIONER") == "clip"):
        return clip_describer()
    return stub_describer


@register_example("multimodal_rag")
class MultimodalRAG(BaseExample):
    def __init__(self, context: ChainContext = None,
                 describer: Optional[ImageDescriber] = None) -> None:
        self.ctx = context or get_context()
        self.describer = describer or get_describer()

    # ------------------------------------------------------------ ingestion

    def _element_docs(self, elements: List[Element]) -> List[Document]:
        docs: List[Document] = []
        splitter = self.ctx.splitter()
        for el in elements:
            if el.kind == "text":
                for chunk in splitter.split(el.text):
                    docs.append(Document(
                        content=chunk,
                        metadata={**el.metadata, "kind": "text"}))
            else:
                try:
                    caption = self.describer(el.image_bytes, el.metadata)
                except Exception as exc:
                    logger.warning("image description failed: %s", exc)
                    caption = stub_describer(el.image_bytes, el.metadata)
                docs.append(Document(
                    content=caption,
                    metadata={**el.metadata, "kind": "image"}))
        return docs

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        if not filename.lower().endswith((".pdf", ".pptx", ".png")):
            raise ValueError(
                f"{filename} is not a valid PDF/PPTX/PNG file. Only "
                f"PDF/PPTX/PNG files are supported for multimodal rag.")
        elements = parse_multimodal(filepath)
        for el in elements:
            el.metadata["source"] = filename
        docs = self._element_docs(elements)
        if not docs:
            raise ValueError(f"no content extracted from {filename}")
        embeddings = self.ctx.embedder.embed_documents(
            [d.content for d in docs])
        self.ctx.store(COLLECTION).add(docs, embeddings)
        n_img = sum(1 for d in docs if d.metadata.get("kind") == "image")
        logger.info("ingested %s: %d text chunks, %d images",
                    filename, len(docs) - n_img, n_img)

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        messages = [{"role": "system",
                     "content": self.ctx.prompts["chat_template"]},
                    {"role": "user", "content": query}]
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        rcfg = self.ctx.config.retriever
        qvec = self.ctx.embedder.embed_queries([query])[0]
        hits = self.ctx.store(COLLECTION).search(
            qvec, top_k=rcfg.top_k, score_threshold=rcfg.score_threshold)
        if not hits:
            yield NO_CONTEXT_MSG
            return
        chunks = []
        for d, _ in hits:
            prefix = ("[image] " if d.metadata.get("kind") == "image" else "")
            chunks.append(prefix + d.content)
        context_text = trim_context(chunks, self.ctx.embedder.tokenizer,
                                    rcfg.max_context_tokens)
        guardrails.record_context(context_text)
        system = self.ctx.prompts["multimodal_rag_template"].format(
            context=context_text)
        messages = [{"role": "system", "content": system},
                    {"role": "user", "content": query}]
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    # ------------------------------------------------------------ documents

    def document_search(self, query: str, num_docs: int = 4) -> List[Dict[str, Any]]:
        qvec = self.ctx.embedder.embed_queries([query])[0]
        hits = self.ctx.store(COLLECTION).search(
            qvec, top_k=num_docs,
            score_threshold=self.ctx.config.retriever.score_threshold)
        return [{"source": str(d.metadata.get("source", "")),
                 "content": d.content, "score": score}
                for d, score in hits]

    def get_documents(self) -> List[str]:
        return self.ctx.store(COLLECTION).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> bool:
        return self.ctx.store(COLLECTION).delete_by_source(filenames) > 0
