"""LLM client seam: in-process TPU engine or remote OpenAI-compatible server.

The reference's chains obtain their LLM through `get_llm()` which returns a
ChatNVIDIA pointed either at a local NIM or the hosted API catalog
(ref: utils.py:366-399 — "the seam" per SURVEY §7.2). Here the same seam
yields `LocalLLM` (direct scheduler calls, zero HTTP) or `RemoteLLM`
(httpx to any /v1 server), both exposing a streaming `chat` iterator.
"""

from __future__ import annotations

import json
import logging
from functools import lru_cache
from typing import Dict, Iterator, Optional, Sequence

from generativeaiexamples_tpu.core.config import get_config
from generativeaiexamples_tpu.observability import slo as slo_mod

logger = logging.getLogger(__name__)


class LocalLLM:
    """Directly drives the in-proc continuous-batching scheduler."""

    def __init__(self, scheduler) -> None:
        self.scheduler = scheduler

    def chat(self, messages: Sequence[Dict[str, str]], max_tokens: int = 256,
             temperature: float = 0.7, top_p: float = 1.0,
             top_k: int = 0, grammar=None,
             stop: Optional[Sequence[str]] = None) -> Iterator[str]:
        from generativeaiexamples_tpu.engine.scheduler import Request

        prompt_ids = self.scheduler.tokenizer.apply_chat_template(list(messages))
        req = Request(prompt_ids=prompt_ids, max_tokens=max_tokens,
                      temperature=temperature, top_p=top_p, top_k=top_k,
                      grammar=grammar, stop=list(stop or []))
        # Lazily submitted (generator body): submission on first pull keeps
        # the invariant that an un-iterated chat() never orphans a
        # generating request on the device. The dataplane's stage overlap
        # comes from the chains issuing sibling stages concurrently
        # (chains/lookahead.py), not from racing submit ahead of the
        # consumer — every call site drains immediately.
        self.scheduler.submit(req)
        yield from self.scheduler.iter_text(req)
        # the scheduler rejects e.g. over-capacity prompts per-request
        # (no silent truncation) — surface that instead of yielding ''
        if req.error:
            raise RuntimeError(f"LLM request failed: {req.error}")

    def chat_tools(self, messages: Sequence[Dict], tools: Sequence[Dict],
                   tool_choice="auto", **sampling) -> Dict:
        """One tool-capable turn → an OpenAI-shaped assistant message:
        {"role": "assistant", "content": str|None, "tool_calls": [...]?}.
        Same prompt-render/parse mechanics as the /v1 server
        (engine/tools.py), minus the HTTP. A forced/required tool_choice
        additionally applies the on-device tool-envelope grammar
        (engine/grammar.py) — the call is token-level guaranteed to parse,
        which is what the tool-calling fine-tune flywheel scores against."""
        from generativeaiexamples_tpu.engine import tools as tools_mod

        msgs = tools_mod.normalize_messages(messages)
        grammar = None
        if tools and tool_choice != "none":
            name = tools_mod.forced_name(tool_choice)
            if name and name not in tools_mod.tool_names(tools):
                # mirror the /v1 server's 400: a typo'd forced name must
                # fail loudly, not run unconstrained toward a nonexistent
                # tool (engine/server.py's chat_completions guard)
                raise ValueError(f"tool_choice names unknown tool {name!r}")
            msgs = tools_mod.inject_tool_prompt(msgs, tools, tool_choice)
            if tool_choice == "required" or name:
                from generativeaiexamples_tpu.engine import (
                    grammar as grammar_mod)
                try:
                    grammar = grammar_mod.Grammar.for_tools_cached(
                        tools, forced=name)
                except grammar_mod.UnsupportedSchema:
                    grammar = None              # prompt+parse fallback
        text = "".join(self.chat(msgs, grammar=grammar, **sampling))
        calls = (tools_mod.parse_tool_calls(text, tools)
                 if tools and tool_choice != "none" else None)
        if calls:
            return {"role": "assistant", "content": None, "tool_calls": calls}
        return {"role": "assistant", "content": text}


class RemoteLLM:
    """OpenAI-compatible /v1 client (the reference's server_url path)."""

    def __init__(self, base_url: str, model: str) -> None:
        self.base_url = base_url.rstrip("/")
        self.model = model

    def chat(self, messages: Sequence[Dict[str, str]], max_tokens: int = 256,
             temperature: float = 0.7, top_p: float = 1.0,
             top_k: int = 0,
             stop: Optional[Sequence[str]] = None) -> Iterator[str]:
        import httpx

        payload = {"model": self.model, "messages": list(messages),
                   "max_tokens": max_tokens, "temperature": temperature,
                   "top_p": top_p, "stream": True}
        if stop:
            payload["stop"] = list(stop)
        # SLO class + remaining deadline + traceparent ride every engine
        # call (observability/slo.py): the engine judges attainment against
        # the budget the CHAIN admitted the request under, not a default
        with httpx.stream("POST", f"{self.base_url}/v1/chat/completions",
                          json=payload, timeout=120.0,
                          headers=slo_mod.outbound_headers()) as resp:
            for line in resp.iter_lines():
                if not line.startswith("data: "):
                    continue
                data = line[len("data: "):]
                if data.strip() == "[DONE]":
                    return
                chunk = json.loads(data)
                choices = chunk.get("choices") or [{}]
                # engine/server.py reports failures as a schema-shaped final
                # chunk (finish_reason="error" + top-level "error") — surface
                # it instead of ending the stream as an apparent success
                if chunk.get("error") or choices[0].get("finish_reason") == "error":
                    raise RuntimeError(
                        f"LLM stream error: {chunk.get('error', 'unknown')}")
                delta = choices[0].get("delta", {})
                content = delta.get("content")
                if content:
                    yield content

    def chat_tools(self, messages: Sequence[Dict], tools: Sequence[Dict],
                   tool_choice="auto", **sampling) -> Dict:
        """One tool-capable turn against the remote /v1 server; returns the
        assistant message (with `tool_calls` when the model called one)."""
        import httpx

        payload = {"model": self.model, "messages": list(messages),
                   "stream": False, **sampling}
        if tools:
            payload["tools"] = list(tools)
            payload["tool_choice"] = tool_choice
        resp = httpx.post(f"{self.base_url}/v1/chat/completions",
                          json=payload, timeout=120.0,
                          headers=slo_mod.outbound_headers())
        resp.raise_for_status()
        data = resp.json()
        return data["choices"][0]["message"]


@lru_cache(maxsize=1)
def _default_scheduler():
    """Build the in-proc engine once per process (tiny model unless a
    checkpoint is configured) — mirrors the reference's cached get_llm
    (utils.py lru_cache pattern)."""
    from generativeaiexamples_tpu.engine.__main__ import build_scheduler

    cfg = get_config()
    tiny = not cfg.engine.checkpoint_dir
    scheduler, _ = build_scheduler(tiny=tiny)
    scheduler.start()
    return scheduler


def get_llm(scheduler=None):
    """The factory chains call (ref utils.py:366): remote when
    APP_LLM_SERVER_URL is set (a comma-separated list selects the
    health-tracked failover pool with mid-stream resume,
    server/failover.py), local TPU engine otherwise."""
    cfg = get_config()
    if cfg.llm.server_url:
        urls = [u.strip() for u in cfg.llm.server_url.split(",") if u.strip()]
        if len(urls) > 1:
            from generativeaiexamples_tpu.server.failover import FailoverLLM
            return FailoverLLM(urls, cfg.llm.model_name)
        return RemoteLLM(urls[0], cfg.llm.model_name)
    return LocalLLM(scheduler if scheduler is not None else _default_scheduler())
