"""Document loaders for ingestion.

The reference leans on UnstructuredFileLoader (ref: basic_rag/langchain/
chains.py:70); in-tree we parse the common formats directly: txt/md, html
(bs4), csv, json, and PDF via a minimal native parser (uncompressed and
Flate-compressed text streams — covers text-first PDFs; scanned/image PDFs
go through the multimodal chain instead).
"""

from __future__ import annotations

import json
import logging
import os
import re
import zlib
from typing import List

logger = logging.getLogger(__name__)


def load_text(path: str) -> str:
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        return fh.read()


def load_html(path: str) -> str:
    from bs4 import BeautifulSoup

    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        soup = BeautifulSoup(fh.read(), "lxml")
    for tag in soup(["script", "style"]):
        tag.decompose()
    return re.sub(r"\n{3,}", "\n\n", soup.get_text("\n")).strip()


def load_csv(path: str) -> str:
    return load_text(path)


def load_json(path: str) -> str:
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        data = json.load(fh)
    return json.dumps(data, indent=1)


# --------------------------------------------------------------------- PDF

_PDF_STREAM = re.compile(rb"stream\r?\n(.*?)\r?\nendstream", re.S)
_PDF_TEXT_OPS = re.compile(
    rb"\((?:[^()\\]|\\.)*\)\s*Tj"      # (text) Tj
    rb"|\[(?:[^\[\]\\]|\\.)*\]\s*TJ"   # [(a)(b)] TJ
    rb"|T\*|Td|TD",
    re.S)
_PDF_STRING = re.compile(rb"\((?:[^()\\]|\\.)*\)")


def _decode_pdf_string(raw: bytes) -> str:
    body = raw[1:-1]
    body = re.sub(rb"\\([nrtbf()\\])",
                  lambda m: {b"n": b"\n", b"r": b"\r", b"t": b"\t", b"b": b"\b",
                             b"f": b"\f", b"(": b"(", b")": b")",
                             b"\\": b"\\"}[m.group(1)], body)
    body = re.sub(rb"\\(\d{1,3})", lambda m: bytes([int(m.group(1), 8) & 0xFF]), body)
    return body.decode("latin-1", errors="replace")


def load_pdf(path: str) -> str:
    """Best-effort text extraction from Tj/TJ operators in content streams."""
    with open(path, "rb") as fh:
        data = fh.read()
    pieces: List[str] = []
    for m in _PDF_STREAM.finditer(data):
        stream = m.group(1)
        if stream[:2] == b"\x78\x9c" or b"FlateDecode" in data[max(0, m.start() - 400):m.start()]:
            try:
                stream = zlib.decompress(stream)
            except zlib.error:
                continue
        if b"Tj" not in stream and b"TJ" not in stream:
            continue
        line: List[str] = []
        for op in _PDF_TEXT_OPS.finditer(stream):
            tok = op.group(0)
            if tok in (b"T*",) or tok.endswith(b"Td") or tok.endswith(b"TD"):
                if line:
                    pieces.append("".join(line))
                    line = []
                continue
            for s in _PDF_STRING.finditer(tok):
                line.append(_decode_pdf_string(s.group(0)))
        if line:
            pieces.append("".join(line))
    text = "\n".join(p for p in pieces if p.strip())
    if not text.strip():
        logger.warning("PDF %s produced no extractable text "
                       "(image-only or unsupported encoding)", path)
    return text


_LOADERS = {
    ".txt": load_text, ".md": load_text, ".rst": load_text, ".py": load_text,
    ".log": load_text, ".html": load_html, ".htm": load_html,
    ".csv": load_csv, ".json": load_json, ".pdf": load_pdf,
}


def load_document(path: str) -> str:
    """Dispatch by extension; unknown types fall back to text."""
    ext = os.path.splitext(path)[1].lower()
    loader = _LOADERS.get(ext, load_text)
    return loader(path)
