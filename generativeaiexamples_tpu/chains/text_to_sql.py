"""text_to_sql — Vanna-style retrieval-augmented SQL generation over sqlite.

Behavioral parity with the reference's text-to-SQL retrievers
(ref: industries/asset_lifecycle_management_agent/src/asset_lifecycle_management_agent/
retrievers/vanna_util.py — NIMVanna = ChromaDB vector store + LLM: `train`
ingests DDL statements, documentation chunks (chunk_documentation:322), and
question→SQL example pairs into separate collections; `ask` retrieves the
relevant schema/docs/examples and prompts the LLM for SQL; also
community/Vanna_with_NVIDIA_AI_Endpoints). ChromaDB is replaced by the
in-proc TPU vector store; the embedder/LLM are the in-proc engines.

Safety: generated SQL executes through a **read-only sqlite authorizer** —
only SELECT/read opcodes are approved, so a hallucinated `DROP TABLE`
(or a prompt-injected one riding in a user question) is rejected by the
database layer itself, not by regex (the reference runs whatever comes
back — `vn.ask` → `run_sql` — and relies on DB permissions).
"""

from __future__ import annotations

import logging
import re
import sqlite3
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from generativeaiexamples_tpu.chains.basic_rag import _sampling
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

DDL_COLLECTION = "sql_ddl"
DOC_COLLECTION = "sql_docs"
PAIR_COLLECTION = "sql_pairs"

SQL_PROMPT = """\
You are an expert SQL analyst for a SQLite database. Write ONE SQL SELECT
statement answering the user's question. Use only tables and columns from
the schema. Output only the SQL, no commentary, no markdown fences.

Schema:
{ddl}

Documentation:
{docs}

Similar questions and their SQL:
{examples}
"""

SUMMARY_PROMPT = """\
Answer the user's question in one or two sentences from these SQL results.

Question: {question}
SQL: {sql}
Columns: {columns}
Rows (first {n}): {rows}
"""

# sqlite authorizer opcodes that a pure read needs
_READ_OK = {sqlite3.SQLITE_SELECT, sqlite3.SQLITE_READ,
            sqlite3.SQLITE_FUNCTION, sqlite3.SQLITE_RECURSIVE}


def _readonly_authorizer(action, *args):
    return (sqlite3.SQLITE_OK if action in _READ_OK else sqlite3.SQLITE_DENY)


def _split_first_statement(sql: str) -> str:
    """Cut at the first ';' OUTSIDE quoted literals (a semicolon inside
    'a;b' must not truncate the statement)."""
    quote = ""
    for i, ch in enumerate(sql):
        if quote:
            if ch == quote:
                quote = ""
        elif ch in ("'", '"'):
            quote = ch
        elif ch == ";":
            return sql[:i]
    return sql


def extract_sql(text: str) -> str:
    """Pull the SQL out of the LLM reply: strip fences/prose, keep the first
    statement (defensive parse — mirrors Vanna's extract_sql semantics)."""
    fence = re.search(r"```(?:sql)?\s*(.+?)```", text, re.DOTALL | re.IGNORECASE)
    if fence:
        text = fence.group(1)
    match = re.search(r"(?is)\b(select|with)\b.*", text)
    if not match:
        return ""
    return _split_first_statement(match.group().strip()).strip()


@register_example("text_to_sql")
class TextToSQL(BaseExample):
    """Retrieval-augmented SQL generation + read-only execution."""

    def __init__(self, context: ChainContext = None,
                 db_path: str = ":memory:") -> None:
        self.ctx = context or get_context()
        self.db_path = db_path
        self._conn: Optional[sqlite3.Connection] = None

    # ------------------------------------------------------------- database

    def connect(self, db_path: str) -> None:
        if self._conn is not None:
            self._conn.close()
        self.db_path = db_path
        self._conn = None

    @property
    def conn(self) -> sqlite3.Connection:
        if self._conn is None:
            self._conn = sqlite3.connect(self.db_path,
                                         check_same_thread=False)
        return self._conn

    def auto_train_schema(self) -> int:
        """Vanna's initVanna bootstrap: read the live schema's DDL out of
        sqlite_master and train on it (ref vanna_util.py:379 trains DDL
        from the training yaml / INFORMATION_SCHEMA)."""
        rows = self.conn.execute(
            "SELECT sql FROM sqlite_master WHERE sql IS NOT NULL").fetchall()
        for (ddl,) in rows:
            self.train(ddl=ddl)
        return len(rows)

    # ------------------------------------------------------------- training

    def train(self, ddl: str = "", documentation: str = "",
              question: str = "", sql: str = "") -> None:
        """Add training items to their collections (ref NIMVanna.train:
        add_ddl / add_documentation / add_question_sql)."""
        if ddl:
            self._add(DDL_COLLECTION, ddl, {"kind": "ddl"})
        if documentation:
            for chunk in self.ctx.splitter().split(documentation):
                self._add(DOC_COLLECTION, chunk, {"kind": "doc"})
        if question and sql:
            self._add(PAIR_COLLECTION, f"Q: {question}\nSQL: {sql}",
                      {"kind": "pair", "question": question})

    def _add(self, collection: str, content: str, meta: Dict[str, str]) -> None:
        doc = Document(content=content, metadata={"source": collection, **meta})
        emb = self.ctx.embedder.embed_documents([content])
        self.ctx.store(collection).add([doc], emb)

    # ------------------------------------------------------------ the chain

    def generate_sql(self, question: str) -> str:
        q_emb = self.ctx.embedder.embed_queries([question])[0]
        ddl = "\n".join(d.content for d, _ in
                        self.ctx.store(DDL_COLLECTION).search(q_emb, top_k=6))
        docs = "\n".join(d.content for d, _ in
                         self.ctx.store(DOC_COLLECTION).search(q_emb, top_k=4))
        examples = "\n\n".join(
            d.content for d, _ in
            self.ctx.store(PAIR_COLLECTION).search(q_emb, top_k=4))
        prompt = SQL_PROMPT.format(ddl=ddl or "(none)", docs=docs or "(none)",
                                   examples=examples or "(none)")
        reply = "".join(self.ctx.llm.chat(
            [{"role": "system", "content": prompt},
             {"role": "user", "content": question}],
            max_tokens=256, temperature=0.0))
        return extract_sql(reply)

    def run_sql(self, sql: str, limit: int = 50
                ) -> Tuple[List[str], List[tuple]]:
        """Execute read-only; returns (columns, rows).

        Each call gets a PRIVATE connection with the authorizer installed
        for its whole life — a shared connection's install/clear dance is
        racy when the chain server streams requests on separate threads
        (one request's teardown would strip another's write protection).
        File databases additionally open with sqlite's mode=ro."""
        if not sql:
            raise ValueError("no SQL statement to run")
        if self.db_path == ":memory:":
            # in-memory DBs are per-connection; reuse the trainer's conn but
            # keep the authorizer installed permanently (reads still pass)
            conn = self.conn
            conn.set_authorizer(_readonly_authorizer)
        else:
            conn = sqlite3.connect(f"file:{self.db_path}?mode=ro", uri=True)
            conn.set_authorizer(_readonly_authorizer)
        try:
            cur = conn.execute(sql)
            rows = cur.fetchmany(limit)
            columns = [d[0] for d in cur.description or []]
        finally:
            if conn is not self._conn:
                conn.close()
        return columns, rows

    def ask(self, question: str) -> Dict[str, Any]:
        """generate → execute → package (ref vn.ask returns sql/df/fig)."""
        sql = self.generate_sql(question)
        columns, rows = self.run_sql(sql)
        return {"sql": sql, "columns": columns, "rows": rows}

    # --------------------------------------------------- BaseExample surface

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        yield from self.ctx.llm.chat(
            list(chat_history) + [{"role": "user", "content": query}],
            **_sampling(llm_settings))

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        """Full flow with NL summarization of the result set; SQL or
        execution errors surface as a polite message, not a stack trace."""
        try:
            result = self.ask(query)
        except (ValueError, sqlite3.Error) as exc:
            yield f"I could not answer that with SQL: {exc}"
            return
        summary = SUMMARY_PROMPT.format(
            question=query, sql=result["sql"], columns=result["columns"],
            rows=result["rows"][:10], n=min(10, len(result["rows"])))
        settings = _sampling(llm_settings)
        settings["temperature"] = 0.0     # factual summarization stays greedy
        yield from self.ctx.llm.chat(
            [{"role": "user", "content": summary}], **settings)

    def ingest_docs(self, filepath: str, filename: str) -> None:
        """Uploaded files become documentation training data."""
        from generativeaiexamples_tpu.chains.loaders import load_document

        self.train(documentation=load_document(filepath))

    def get_documents(self) -> List[str]:
        return self.ctx.store(DOC_COLLECTION).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> None:
        for coll in (DDL_COLLECTION, DOC_COLLECTION, PAIR_COLLECTION):
            self.ctx.store(coll).delete_by_source(filenames)
