"""agentic_rag — self-corrective RAG with hybrid retrieval.

Behavioral parity with the reference's agentic notebook (ref: RAG/notebooks/
langchain/agentic_rag_with_nemo_retriever_nim.ipynb): hybrid BM25 + dense
ensemble retrieval (cells ~227-235, EnsembleRetriever), a retrieval grader
that filters irrelevant documents, question rewriting when retrieval fails,
generation, a hallucination grader checking groundedness, and an answer
grader checking usefulness — wired as a state machine (LangGraph build,
cells 13-37) with bounded retries.

In-tree the graph is an explicit loop: retrieve → grade docs →
(rewrite + retry | generate) → grade generation → (accept | regenerate |
rewrite + retry), capped at `max_retries` passes.
"""

from __future__ import annotations

import json
import logging
from typing import Any, Dict, Iterator, List, Sequence

from generativeaiexamples_tpu.chains.basic_rag import _sampling, trim_context
from generativeaiexamples_tpu.server import guardrails
from generativeaiexamples_tpu.chains.context import ChainContext, get_context
from generativeaiexamples_tpu.chains.loaders import load_document
from generativeaiexamples_tpu.chains.lookahead import (
    DEFAULT_SIM_THRESHOLD, LookaheadRetrieval)
from generativeaiexamples_tpu.chains.query_decomposition import extract_json
from generativeaiexamples_tpu.core.tracing import chain_instrumentation
from generativeaiexamples_tpu.observability.otel import stage_span
from generativeaiexamples_tpu.retrieval.bm25 import (
    BM25Index, reciprocal_rank_fusion)
from generativeaiexamples_tpu.retrieval.store import Document
from generativeaiexamples_tpu.server.base import BaseExample
from generativeaiexamples_tpu.server.registry import register_example

logger = logging.getLogger(__name__)

from generativeaiexamples_tpu.chains import NO_CONTEXT_MSG

COLLECTION = "agentic_rag"
MAX_RETRIES = 2


@register_example("agentic_rag")
class AgenticRAG(BaseExample):
    def __init__(self, context: ChainContext = None) -> None:
        self.ctx = context or get_context()
        self.bm25 = BM25Index()
        self._bm25_docs: List[Document] = []

    # ------------------------------------------------------------ ingestion

    @chain_instrumentation
    def ingest_docs(self, filepath: str, filename: str) -> None:
        text = load_document(filepath)
        if not text.strip():
            raise ValueError(f"no text extracted from {filename}")
        chunks = self.ctx.splitter().split(text)
        docs = [Document(content=c, metadata={"source": filename})
                for c in chunks]
        embeddings = self.ctx.embedder.embed_documents([d.content for d in docs])
        self.ctx.store(COLLECTION).add(docs, embeddings)
        self.bm25.add([d.content for d in docs])
        self._bm25_docs.extend(docs)

    # ------------------------------------------------------------ retrieval

    def _hybrid_retrieve(self, query: str, top_k: int) -> List[Document]:
        return self._hybrid_with_vec(query, top_k)[1]

    def _hybrid_with_vec(self, query: str, top_k: int, qvec=None):
        """BM25 + dense, fused by reciprocal rank (the EnsembleRetriever
        equivalent). Returns (qvec, docs) — the vector feeds the lookahead
        reconcile (chains/lookahead.py), which passes it back on a requery
        so the query is never embedded twice."""
        if qvec is None:
            qvec = self.ctx.embedder.embed_queries([query])[0]
        dense_hits = self.ctx.store(COLLECTION).search(
            qvec, top_k=top_k * 2, score_threshold=0.0)
        sparse_hits = self.bm25.search(query, top_k=top_k * 2)

        # fuse over content identity
        pool: List[Document] = []
        key_to_idx: Dict[str, int] = {}

        def pool_idx(doc: Document) -> int:
            key = doc.content
            if key not in key_to_idx:
                key_to_idx[key] = len(pool)
                pool.append(doc)
            return key_to_idx[key]

        dense_rank = [pool_idx(d) for d, _ in dense_hits]
        sparse_rank = [pool_idx(self._bm25_docs[i]) for i, _ in sparse_hits]
        fused = reciprocal_rank_fusion([dense_rank, sparse_rank], top_k=top_k)
        return qvec, [pool[i] for i in fused]

    def _rewrite_with_lookahead(self, question: str, top_k: int,
                                held, reuse_similar: bool, **settings: Any):
        """Run the question-rewrite LLM call with the CURRENT question's
        in-hand retrieval seeded as the speculation (TeleRAG reconcile,
        chains/lookahead.py). ``held`` is this iteration's already-computed
        ``(qvec, ungraded_docs)`` — seeding it costs ZERO new encoder/store
        work; retrieval runs again only when the rewrite diverges. Returns
        (rewritten_question, (qvec, docs) valid for it).

        ``reuse_similar=False`` forces the re-retrieve whenever the rewrite
        changed the text at all — used on the docs-rejected path, where the
        held docs were just graded irrelevant and a merely-similar rewrite
        must hit BM25/dense afresh. An identical rewrite still reuses them
        (same query → same docs, by construction)."""
        look = LookaheadRetrieval(
            lambda q, v=None: self._hybrid_with_vec(q, top_k, v),
            sim_threshold=(DEFAULT_SIM_THRESHOLD if reuse_similar else 2.0))
        look.seed(question, held)
        with stage_span("rewrite"):
            rewritten = self._rewrite_question(question, **settings)
        with stage_span("retrieve"):
            qvec, docs = look.reconcile(
                rewritten,
                embed=(lambda q: self.ctx.embedder.embed_queries([q])[0])
                if reuse_similar else None)
        return rewritten, (qvec, docs)

    # -------------------------------------------------------------- graders

    def _grade(self, prompt: str, **settings: Any) -> bool:
        s = _sampling(settings)
        s.update(max_tokens=32, temperature=0.0)
        raw = "".join(self.ctx.llm.chat(
            [{"role": "user", "content": prompt}], **s))
        parsed = extract_json(raw)
        if parsed and "score" in parsed:
            return str(parsed["score"]).strip().lower().startswith("y")
        return "yes" in raw.lower()

    def _grade_documents(self, question: str, docs: List[Document],
                         **settings: Any) -> List[Document]:
        kept = []
        for doc in docs:
            prompt = self.ctx.prompts["retrieval_grader_prompt"].format(
                document=doc.content, question=question)
            if self._grade(prompt, **settings):
                kept.append(doc)
        logger.info("retrieval grader kept %d/%d docs", len(kept), len(docs))
        return kept

    def _rewrite_question(self, question: str, **settings: Any) -> str:
        s = _sampling(settings)
        s.update(max_tokens=96, temperature=0.0)
        out = "".join(self.ctx.llm.chat(
            [{"role": "user",
              "content": self.ctx.prompts["question_rewriter_prompt"].format(
                  question=question)}], **s)).strip()
        return out or question

    def _generate(self, question: str, context_text: str,
                  **settings: Any) -> str:
        system = self.ctx.prompts["rag_template"].format(context=context_text)
        return "".join(self.ctx.llm.chat(
            [{"role": "system", "content": system},
             {"role": "user", "content": question}], **_sampling(settings)))

    # ----------------------------------------------------------- generation

    @chain_instrumentation
    def llm_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        messages = ([{"role": "system",
                      "content": self.ctx.prompts["chat_template"]}]
                    + list(chat_history) + [{"role": "user", "content": query}])
        yield from self.ctx.llm.chat(messages, **_sampling(llm_settings))

    @chain_instrumentation
    def rag_chain(self, query: str, chat_history: Sequence[Dict[str, str]],
                  **llm_settings: Any) -> Iterator[str]:
        rcfg = self.ctx.config.retriever
        question = query
        generation = ""
        held = None    # (qvec, ungraded docs) for the CURRENT question
        for attempt in range(MAX_RETRIES + 1):
            if held is None:
                with stage_span("retrieve"):
                    held = self._hybrid_with_vec(question, rcfg.top_k)
            raw_docs = held[1]
            docs = self._grade_documents(question, raw_docs, **llm_settings)
            if not docs:
                if attempt >= MAX_RETRIES:
                    yield NO_CONTEXT_MSG
                    return
                # docs were graded irrelevant: only an IDENTICAL rewrite may
                # reuse this iteration's retrieval (reuse_similar=False)
                question, held = self._rewrite_with_lookahead(
                    question, rcfg.top_k, held, reuse_similar=False,
                    **llm_settings)
                logger.info("no relevant docs; rewrote question to %r",
                            question)
                continue
            context_text = trim_context(
                [d.content for d in docs], self.ctx.embedder.tokenizer,
                rcfg.max_context_tokens)
            guardrails.record_context(context_text)
            generation = self._generate(question, context_text,
                                        **llm_settings)
            grounded = self._grade(
                self.ctx.prompts["hallucination_grader_prompt"].format(
                    documents=context_text, generation=generation),
                **llm_settings)
            useful = grounded and self._grade(
                self.ctx.prompts["answer_grader_prompt"].format(
                    generation=generation, question=question),
                **llm_settings)
            if useful or attempt >= MAX_RETRIES:
                break
            if grounded:  # answered but not useful → rewrite the question;
                # these docs PASSED grading, so a similar rewrite may reuse
                # the held retrieval (reuse_similar=True)
                question, held = self._rewrite_with_lookahead(
                    question, rcfg.top_k, held, reuse_similar=True,
                    **llm_settings)
            # not grounded: regenerate the SAME question — `held` already
            # carries its retrieval, so the next iteration re-grades without
            # recomputing it (the store is deterministic)
            logger.info("generation rejected (grounded=%s); retrying",
                        grounded)
        yield generation or NO_CONTEXT_MSG

    # ------------------------------------------------------------ documents

    def document_search(self, query: str, num_docs: int = 4) -> List[Dict[str, Any]]:
        docs = self._hybrid_retrieve(query, num_docs)
        return [{"source": str(d.metadata.get("source", "")),
                 "content": d.content, "score": 0.0} for d in docs]

    def get_documents(self) -> List[str]:
        return self.ctx.store(COLLECTION).list_sources()

    def delete_documents(self, filenames: Sequence[str]) -> bool:
        removed = self.ctx.store(COLLECTION).delete_by_source(filenames) > 0
        names = set(filenames)
        keep = [d for d in self._bm25_docs
                if d.metadata.get("source") not in names]
        if len(keep) != len(self._bm25_docs):
            self.bm25 = BM25Index()
            self.bm25.add([d.content for d in keep])
            self._bm25_docs = keep
        return removed
