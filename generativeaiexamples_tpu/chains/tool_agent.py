"""tool_agent — OpenAI-tool-calling agent loop with human-in-the-loop gates.

Capability parity with the reference's tool-calling agent notebooks
(ref: RAG/notebooks/langchain/Agent_use_tools_leveraging_NVIDIA_AI_endpoints
.ipynb — an LLM bound to typed tools loops call → result → call until it
answers; ref: RAG/notebooks/langchain/NIM_tool_call_HumanInTheLoop_
MultiAgents.ipynb — sensitive tools interrupt the loop and wait for a human
verdict before executing; ref: LangGraph_HandlingAgent_IntermediateSteps
.ipynb — intermediate steps surface as a typed event stream).

The LangGraph runtime is replaced by a plain resumable generator: `run`
yields typed events ({"type": "tool_call" | "tool_result" |
"approval_request" | "final"}); when a tool marked `requires_approval`
comes up, the loop emits an `approval_request` carrying a serializable
`PendingApproval` and STOPS. `resume(pending, approved)` picks the episode
back up with the human verdict — deny feeds the model a refusal message
(it can re-plan), approve executes. Deny-by-default posture matches
chains/bash_agent.py: nothing sensitive runs without an explicit verdict.
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

logger = logging.getLogger(__name__)

DEFAULT_SYSTEM = ("You are a helpful assistant. Use the available tools "
                  "when they help; answer directly when they don't.")


@dataclass
class Tool:
    """A typed callable the agent may invoke."""

    name: str
    description: str
    parameters: Dict[str, Any]            # JSON schema for the arguments
    fn: Callable[..., str]
    requires_approval: bool = False       # HITL gate

    def spec(self) -> Dict[str, Any]:
        return {"type": "function", "function": {
            "name": self.name, "description": self.description,
            "parameters": self.parameters}}


@dataclass
class PendingApproval:
    """Everything needed to resume an interrupted episode (json-able via
    `to_json`/`from_json`, so the wait can cross a process boundary)."""

    messages: List[Dict[str, Any]]
    call: Dict[str, Any]                  # the tool_call awaiting a verdict
    remaining: List[Dict[str, Any]] = field(default_factory=list)
    steps: int = 0

    def to_json(self) -> str:
        return json.dumps({"messages": self.messages, "call": self.call,
                           "remaining": self.remaining, "steps": self.steps})

    @classmethod
    def from_json(cls, blob: str) -> "PendingApproval":
        d = json.loads(blob)
        return cls(messages=d["messages"], call=d["call"],
                   remaining=d["remaining"], steps=d["steps"])


class ToolAgent:
    """Drives a tool-capable LLM (`chat_tools` seam, chains/llm_client.py)."""

    def __init__(self, llm, tools: Sequence[Tool], max_steps: int = 6,
                 system_prompt: str = DEFAULT_SYSTEM,
                 **sampling: Any) -> None:
        self.llm = llm
        self.tools = {t.name: t for t in tools}
        self.max_steps = max_steps
        self.system_prompt = system_prompt
        self.sampling = sampling

    # ------------------------------------------------------------------ API

    def run(self, query: str,
            history: Sequence[Dict[str, str]] = ()) -> Iterator[Dict]:
        messages = ([{"role": "system", "content": self.system_prompt}]
                    + list(history) + [{"role": "user", "content": query}])
        yield from self._drive(messages, [], 0)

    def resume(self, pending: PendingApproval, approved: bool,
               feedback: str = "") -> Iterator[Dict]:
        """Continue after a human verdict on ``pending.call``."""
        messages = list(pending.messages)
        call = pending.call
        if approved:
            yield {"type": "tool_call", "call": call, "approved": True}
            result = self._execute(call)
            yield {"type": "tool_result",
                   "name": call["function"]["name"], "content": result}
        else:
            result = ("Tool call denied by the user."
                      + (f" Feedback: {feedback}" if feedback else ""))
            yield {"type": "tool_result",
                   "name": call["function"]["name"], "content": result}
        messages.append(self._tool_message(call, result))
        yield from self._drive(messages, list(pending.remaining),
                               pending.steps)

    # ------------------------------------------------------------ internals

    @staticmethod
    def _tool_message(call: Dict[str, Any], content: str) -> Dict[str, Any]:
        return {"role": "tool", "tool_call_id": call.get("id", ""),
                "name": call["function"]["name"], "content": content}

    def _execute(self, call: Dict[str, Any]) -> str:
        name = call["function"]["name"]
        tool = self.tools[name]
        try:
            args = json.loads(call["function"].get("arguments") or "{}")
            if not isinstance(args, dict):
                args = {"value": args}
        except ValueError:
            return f"error: arguments for {name} were not valid JSON"
        try:
            return str(tool.fn(**args))
        except Exception as exc:  # tool errors feed back, never crash the loop
            logger.exception("tool %s failed", name)
            return f"error: {exc}"

    def _drive(self, messages: List[Dict], queue: List[Dict],
               steps: int) -> Iterator[Dict]:
        while True:
            while queue:
                call = queue.pop(0)
                name = call["function"]["name"]
                tool = self.tools.get(name)
                if tool is None:
                    result = f"error: unknown tool {name!r}"
                elif tool.requires_approval:
                    yield {"type": "approval_request", "call": call,
                           "pending": PendingApproval(
                               messages=[dict(m) for m in messages],
                               call=call, remaining=list(queue),
                               steps=steps)}
                    return   # interrupted: resume() continues the episode
                else:
                    yield {"type": "tool_call", "call": call}
                    result = self._execute(call)
                    yield {"type": "tool_result", "name": name,
                           "content": result}
                messages.append(self._tool_message(call, result))
            if steps >= self.max_steps:
                yield {"type": "final",
                       "content": "I could not finish within the step "
                                  "budget.", "exhausted": True}
                return
            msg = self.llm.chat_tools(
                messages, [t.spec() for t in self.tools.values()],
                tool_choice="auto", **self.sampling)
            if msg.get("tool_calls"):
                messages.append(msg)
                queue = list(msg["tool_calls"])
                steps += 1
                continue
            yield {"type": "final", "content": msg.get("content") or ""}
            return
