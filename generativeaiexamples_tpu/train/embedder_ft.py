"""Contrastive fine-tuning of the bi-encoder embedder (the data flywheel).

Behavioral parity with the reference's embedding-model customization loop
(ref: nemo/data-flywheel/embedding-finetuning/*.ipynb — fine-tune the
retriever's embedding NIM on harvested (query, passage) pairs via the NeMo
Customizer microservice, then evaluate recall with the Evaluator service).
Here the whole loop is in-tree and TPU-native:

  * **objective** — symmetric InfoNCE with in-batch negatives: each query's
    positive is its paired passage; every other passage in the batch is a
    negative (and vice versa). This is the e5-family training recipe and
    needs no negative mining to start improving retrieval.
  * **execution** — one jitted train step (loss + AdamW update) over the
    functional BERT tower (models/bert.py); batch-axis data parallelism
    falls out of pjit sharding when a mesh is supplied.
  * **evaluation** — recall@k over a held-out set, computed before and
    after so the flywheel's value is a printed fact, not a hope.

Input rows are `{"question": ..., "context": ...}` dicts — exactly what
`evaluation.sdg.run_sdg_pipeline` exports (train.json), closing the loop:
serve → harvest/synthesize → filter → fine-tune → serve better.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from generativeaiexamples_tpu.encoders.embedder import (
    PASSAGE_PREFIX, QUERY_PREFIX, Embedder)
from generativeaiexamples_tpu.models import bert

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class EmbedFTConfig:
    batch_size: int = 32          # in-batch negatives: bigger = harder task
    max_len: int = 128
    steps: int = 200
    learning_rate: float = 2e-5
    warmup_steps: int = 20
    temperature: float = 0.05     # InfoNCE logit scale (e5 default 0.01-0.05)
    seed: int = 0


def _tokenize_batch(tokenizer, texts: Sequence[str], max_len: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    ids = [tokenizer.encode(t)[:max_len] for t in texts]
    # bucket the sequence axis to powers of two (same reason as
    # Embedder._batchify): jit keys on shape, and exact-length padding
    # would recompile the full fwd+bwd+AdamW graph per distinct length
    S = 8
    longest = max(2, max(len(i) for i in ids))
    while S < longest:
        S *= 2
    S = min(S, max_len)
    tokens = np.zeros((len(ids), S), np.int32)
    mask = np.zeros((len(ids), S), bool)
    for r, seq in enumerate(ids):
        seq = seq[:S]
        tokens[r, :len(seq)] = seq
        mask[r, :len(seq)] = True
        if not seq:
            mask[r, 0] = True
    return tokens, mask


def info_nce_loss(params, cfg: bert.BertConfig, q_tokens, q_mask,
                  p_tokens, p_mask, temperature: float) -> jnp.ndarray:
    """Symmetric in-batch-negative InfoNCE."""
    q = bert.embed(params, cfg, q_tokens, q_mask)        # (B, D) normalized
    p = bert.embed(params, cfg, p_tokens, p_mask)
    logits = (q @ p.T) / temperature                     # (B, B)
    labels = jnp.arange(q.shape[0])
    loss_qp = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    loss_pq = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels)
    return (loss_qp.mean() + loss_pq.mean()) / 2.0


class EmbedderTrainer:
    """Drives the contrastive fine-tune; returns a ready-to-serve Embedder."""

    def __init__(self, cfg: Optional[bert.BertConfig] = None,
                 params: Optional[bert.Params] = None,
                 tokenizer=None, ft_cfg: EmbedFTConfig = EmbedFTConfig()
                 ) -> None:
        from generativeaiexamples_tpu.engine.tokenizer import get_tokenizer

        self.cfg = cfg or bert.BertConfig.tiny()
        self.params = params if params is not None else bert.init_params(
            jax.random.PRNGKey(11), self.cfg)
        self.tokenizer = tokenizer or get_tokenizer("")
        self.ft = ft_cfg
        schedule = optax.warmup_cosine_decay_schedule(
            0.0, ft_cfg.learning_rate, ft_cfg.warmup_steps,
            max(ft_cfg.steps, ft_cfg.warmup_steps + 1))
        self.opt = optax.adamw(schedule, weight_decay=0.01)
        self.opt_state = self.opt.init(self.params)

        def step(params, opt_state, q_t, q_m, p_t, p_m):
            loss, grads = jax.value_and_grad(info_nce_loss)(
                params, self.cfg, q_t, q_m, p_t, p_m, ft_cfg.temperature)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._step = jax.jit(step)

    # ---------------------------------------------------------------- data

    def _batches(self, rows: Sequence[Dict], rng: np.random.RandomState):
        """Endless shuffled (query, passage) token batches with the e5
        prefixes the serving embedder applies (train/serve symmetry)."""
        B = self.ft.batch_size
        while True:
            order = rng.permutation(len(rows))
            for start in range(0, len(rows) - B + 1, B):
                batch = [rows[i] for i in order[start:start + B]]
                q_t, q_m = _tokenize_batch(
                    self.tokenizer,
                    [QUERY_PREFIX + r["question"] for r in batch],
                    self.ft.max_len)
                p_t, p_m = _tokenize_batch(
                    self.tokenizer,
                    [PASSAGE_PREFIX + r["context"] for r in batch],
                    self.ft.max_len)
                yield q_t, q_m, p_t, p_m

    # ---------------------------------------------------------------- train

    def fit(self, rows: Sequence[Dict], on_step=None) -> List[float]:
        if len(rows) < self.ft.batch_size:
            raise ValueError(
                f"need >= batch_size ({self.ft.batch_size}) rows for "
                f"in-batch negatives; got {len(rows)}")
        rng = np.random.RandomState(self.ft.seed)
        losses: List[float] = []
        gen = self._batches(rows, rng)
        for step_i in range(self.ft.steps):
            q_t, q_m, p_t, p_m = next(gen)
            self.params, self.opt_state, loss = self._step(
                self.params, self.opt_state, jnp.asarray(q_t),
                jnp.asarray(q_m), jnp.asarray(p_t), jnp.asarray(p_m))
            losses.append(float(loss))
            if on_step:
                on_step(step_i, losses[-1])
        logger.info("embedder fine-tune: loss %.4f -> %.4f over %d steps",
                    losses[0], losses[-1], len(losses))
        return losses

    def to_embedder(self, **kw) -> Embedder:
        return Embedder(cfg=self.cfg, params=self.params,
                        tokenizer=self.tokenizer, **kw)


# ------------------------------------------------------------------- eval

def recall_at_k(embedder: Embedder, rows: Sequence[Dict], k: int = 1
                ) -> float:
    """Each question must retrieve its own context among the UNIQUE
    contexts (the Evaluator-service recall check of the flywheel loop).
    Contexts are deduped to ids first — SDG emits multiple QAs per chunk,
    and scoring against duplicate rows would cap a perfect embedder at
    1/duplicates recall on tie-broken identical vectors."""
    if not rows:
        return 0.0
    doc_ids: Dict[str, int] = {}
    row_doc = []
    for r in rows:
        row_doc.append(doc_ids.setdefault(r["context"], len(doc_ids)))
    contexts = list(doc_ids)
    q = np.asarray(embedder.embed_queries([r["question"] for r in rows]))
    p = np.asarray(embedder.embed_documents(contexts))
    sims = q @ p.T                                  # (rows, unique docs)
    top = np.argsort(-sims, axis=1)[:, :k]
    hits = sum(1 for i in range(len(rows)) if row_doc[i] in top[i])
    return hits / len(rows)
