"""SFT data pipeline: jsonl prompt/completion → packed token batches.

Replaces the reference's NeMo data prep (ref: finetuning/Gemma/lora.ipynb
"Step 2: Prepare the data" — PubMedQA converted to
`{"input": ..., "output": ...}` jsonl consumed by
`megatron_gpt_finetuning_config`'s train_ds). Same on-disk contract
(jsonl with input/output or prompt/completion keys); tokenization and
batching are host-side Python feeding jit-shaped arrays:

  * loss is masked over prompt tokens (train on completions only, the
    SFT convention NeMo applies via `answer_only_loss`);
  * fixed (batch, seq_len) shapes — right padding, one compiled train step;
  * deterministic shuffling per epoch from a seed, so runs are replayable.
"""

from __future__ import annotations

import json
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Sequence, Tuple

import numpy as np

Encode = Callable[[str], List[int]]


@dataclass(frozen=True)
class SFTExample:
    prompt: str
    completion: str


def load_jsonl(path: str) -> List[SFTExample]:
    """Accepts {"prompt","completion"} or NeMo-style {"input","output"} rows."""
    out: List[SFTExample] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            prompt = row.get("prompt", row.get("input"))
            completion = row.get("completion", row.get("output"))
            if prompt is None or completion is None:
                raise ValueError(f"row missing prompt/completion keys: {row.keys()}")
            out.append(SFTExample(prompt=prompt, completion=completion))
    return out


def load_jsonl_with(path: str, formatter) -> List[SFTExample]:
    """Load raw dataset rows (e.g. PubMedQA/Alpaca) through a
    recipes.FORMATTERS entry instead of expecting prompt/completion keys."""
    out: List[SFTExample] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(formatter(json.loads(line)))
    return out


def encode_example(ex: SFTExample, encode: Encode, bos_id: int | None,
                   eos_id: int | None, max_len: int) -> Tuple[List[int], List[int]]:
    """Token ids + loss mask (1 on completion tokens and EOS, 0 on prompt)."""
    prompt_ids = ([bos_id] if bos_id is not None else []) + encode(ex.prompt)
    comp_ids = encode(ex.completion) + ([eos_id] if eos_id is not None else [])
    ids = (prompt_ids + comp_ids)[:max_len]
    mask = ([0] * len(prompt_ids) + [1] * len(comp_ids))[:max_len]
    return ids, mask


@dataclass(frozen=True)
class Batch:
    """tokens/loss_mask: (B, S) int32/float32 host arrays (np, fed to jit)."""

    tokens: np.ndarray
    loss_mask: np.ndarray

    @property
    def supervised_tokens(self) -> int:
        return int(self.loss_mask.sum())


def batches(examples: Sequence[SFTExample], encode: Encode, *,
            batch_size: int, seq_len: int, bos_id: int | None = None,
            eos_id: int | None = None, epochs: int = 1,
            seed: int = 0, drop_remainder: bool = True) -> Iterator[Batch]:
    """Yield fixed-shape right-padded batches; shuffled each epoch."""
    encoded = [encode_example(ex, encode, bos_id, eos_id, seq_len + 1)
               for ex in examples]
    rng = random.Random(seed)
    order = list(range(len(encoded)))
    for _ in range(epochs):
        rng.shuffle(order)
        for i in range(0, len(order), batch_size):
            idx = order[i:i + batch_size]
            if len(idx) < batch_size:
                if drop_remainder:
                    continue
                while len(idx) < batch_size:  # wrap-fill from the remainder
                    idx = idx + idx[: batch_size - len(idx)]
            # +1: the train step shifts (predict t+1 from ≤t)
            tokens = np.zeros((batch_size, seq_len + 1), np.int32)
            mask = np.zeros((batch_size, seq_len + 1), np.float32)
            for r, j in enumerate(idx):
                ids, m = encoded[j]
                tokens[r, :len(ids)] = ids
                mask[r, :len(m)] = m
            yield Batch(tokens=tokens, loss_mask=mask)
