"""LoRA adapters for the stacked-layer Llama pytree.

TPU-native replacement for the reference's NeMo LoRA path
(ref: finetuning/Gemma/lora.ipynb cells 34-36 `model.add_adapter(
LoraPEFTConfig(model_cfg))`, cell 48 merge via
`scripts/nlp_language_modeling/merge_lora_weights/merge.py`). There the
adapter lives inside Megatron modules and NCCL shards it; here it is a
separate pytree threaded through `models.llama` (`_maybe_lora`), so:

  * the base params stay frozen device buffers — the optimizer state covers
    only the adapter (tiny), which is what makes LoRA cheap;
  * serving merged vs unmerged is the same code path (`merge_adapters` folds
    the low-rank product into the base weights for zero-overhead inference);
  * adapters are stacked on a leading layer axis like the base params, so the
    model's `lax.scan` slices them per layer, and sharding is the same
    rule-table mechanism (`adapter_logical_axes`).

Parameterization: the effective update is ``x @ a @ b`` with
``a ~ N(0, 1/in) * (alpha/rank)`` and ``b = 0`` — the conventional
(alpha/rank) scale is folded into ``a``'s init instead of multiplying the
product every step (same function class; at init the product is zero either
way, matching LoRA's identity-at-start property).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.models import llama

Params = Dict[str, Any]

# target name → (in_dim, out_dim) extractors on LlamaConfig
_TARGET_DIMS = {
    "wq": lambda c: (c.dim, c.n_heads * c.head_dim),
    "wk": lambda c: (c.dim, c.n_kv_heads * c.head_dim),
    "wv": lambda c: (c.dim, c.n_kv_heads * c.head_dim),
    "wo": lambda c: (c.n_heads * c.head_dim, c.dim),
    "w_gate": lambda c: (c.dim, c.hidden_dim),
    "w_up": lambda c: (c.dim, c.hidden_dim),
    "w_down": lambda c: (c.hidden_dim, c.dim),
}

# logical axis names of each target's (in, out) dims, for sharding rules
_TARGET_AXES = {
    "wq": ("embed", "heads"),
    "wk": ("embed", "kv_heads"),
    "wv": ("embed", "kv_heads"),
    "wo": ("heads", "embed"),
    "w_gate": ("embed", "mlp"),
    "w_up": ("embed", "mlp"),
    "w_down": ("mlp", "embed"),
}


@dataclass(frozen=True)
class LoraConfig:
    """Adapter spec; defaults mirror common attention-only LoRA (the
    reference's NeMo `LoraPEFTConfig` targets attention projections)."""

    rank: int = 8
    alpha: float = 16.0
    targets: Tuple[str, ...] = ("wq", "wk", "wv", "wo")

    def __post_init__(self):
        unknown = set(self.targets) - set(_TARGET_DIMS)
        if unknown:
            raise ValueError(f"unknown LoRA targets {sorted(unknown)}; "
                             f"valid: {sorted(_TARGET_DIMS)}")


def init_adapters(rng: jax.Array, model_cfg: llama.LlamaConfig,
                  cfg: LoraConfig, dtype: Any = jnp.float32) -> Params:
    """Adapter pytree {target: {"a": (L, in, r), "b": (L, r, out)}}."""
    if model_cfg.mlp != "glu" and "w_gate" in cfg.targets:
        # fail at startup, not at merge time after the full training run:
        # plain-MLP models (StarCoder2) have no w_gate to fold the adapter
        # into (use the lora_starcoder2 recipe's target set)
        raise ValueError(
            f"LoRA target 'w_gate' does not exist in a mlp={model_cfg.mlp!r} "
            "model; drop it from targets")
    moe_mlp_targets = {"w_gate", "w_up", "w_down"} & set(cfg.targets)
    if model_cfg.mlp == "moe" and moe_mlp_targets:
        # the MoE block bypasses _proj for its expert MLP: dense-shaped
        # adapters on these names would train nothing (zero grads) and
        # corrupt the 4-D expert weights at merge time
        raise ValueError(
            f"LoRA MLP targets {sorted(moe_mlp_targets)} are not supported "
            "on mlp='moe' models; restrict targets to attention "
            "projections (wq/wk/wv/wo)")
    L = model_cfg.n_layers
    scale = cfg.alpha / cfg.rank
    keys = jax.random.split(rng, len(cfg.targets))
    adapters: Params = {}
    for key, name in zip(keys, cfg.targets):
        d_in, d_out = _TARGET_DIMS[name](model_cfg)
        a = jax.random.normal(key, (L, d_in, cfg.rank), jnp.float32)
        a = (a / math.sqrt(d_in) * scale).astype(dtype)
        adapters[name] = {"a": a, "b": jnp.zeros((L, cfg.rank, d_out), dtype)}
    return adapters


def adapter_logical_axes(cfg: LoraConfig) -> Params:
    """Logical annotations matching `init_adapters` (rank dim replicated)."""
    ax: Params = {}
    for name in cfg.targets:
        in_ax, out_ax = _TARGET_AXES[name]
        ax[name] = {"a": (None, in_ax, None), "b": (None, None, out_ax)}
    return ax


def save_adapters(directory: str, adapters: Params, cfg: LoraConfig) -> None:
    """Persist an adapter tree + its LoraConfig sidecar — the unmerged
    artifact the serving engine's per-request multi-LoRA loads directly
    (replacing the reference's merge→re-export flow,
    finetuning/Gemma/lora.ipynb cell 48, when the adapter should stay
    hot-swappable)."""
    import json as _json
    import os as _os

    from generativeaiexamples_tpu.train.checkpoints import save_params
    save_params(directory, adapters)
    with open(_os.path.join(directory, "lora.json"), "w",
              encoding="utf-8") as fh:
        _json.dump({"rank": cfg.rank, "alpha": cfg.alpha,
                    "targets": list(cfg.targets)}, fh)


def load_adapters(directory: str, model_cfg: llama.LlamaConfig) -> Params:
    """Restore an adapter tree saved by :func:`save_adapters` (the
    lora.json sidecar reconstructs the shape template)."""
    import json as _json
    import os as _os

    import jax as _jax

    from generativeaiexamples_tpu.train.checkpoints import load_params
    with open(_os.path.join(directory, "lora.json"), encoding="utf-8") as fh:
        meta = _json.load(fh)
    cfg = LoraConfig(rank=int(meta["rank"]), alpha=float(meta["alpha"]),
                     targets=tuple(meta["targets"]))
    template = _jax.eval_shape(
        lambda: init_adapters(_jax.random.PRNGKey(0), model_cfg, cfg))
    return load_params(directory, model_cfg, target=template)


def merge_adapters(params: Params, adapters: Params) -> Params:
    """Fold each low-rank product into the base weight: W' = W + a@b.

    Parity with the reference's merge step (Gemma/lora.ipynb cell 48) —
    the merged tree serves with zero adapter overhead.
    """
    merged_layers = dict(params["layers"])
    for name, ab in adapters.items():
        w = merged_layers[name]
        delta = jnp.einsum("lir,lro->lio", ab["a"].astype(jnp.float32),
                           ab["b"].astype(jnp.float32))
        merged_layers[name] = (w.astype(jnp.float32) + delta).astype(w.dtype)
    out = dict(params)
    out["layers"] = merged_layers
    return out
