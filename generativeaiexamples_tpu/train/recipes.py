"""Fine-tuning recipes: named presets matching the reference notebooks.

The reference ships one notebook per recipe, each a hydra-config variation on
the same NeMo/Megatron path (ref: finetuning/Gemma/lora.ipynb cells 26-28 —
LoRA, mbs=1, gbs=8, bf16, max_steps 50; finetuning/Gemma/sft.ipynb —
full-parameter SFT; finetuning/Codegemma/lora.ipynb;
finetuning/StarCoder2/lora.ipynb; finetuning/NeMo/slm — small-LM pretrain+SFT).
Here each recipe is a `TrainConfig` preset + a prompt formatter for its
dataset shape; all run through the one `Trainer`.

PubMedQA formatting mirrors the reference's data prep (Gemma/lora.ipynb
"Step 2": question+context → long-answer jsonl).
"""

from __future__ import annotations

from typing import Callable, Dict

from generativeaiexamples_tpu.train.data import SFTExample
from generativeaiexamples_tpu.train.lora import LoraConfig
from generativeaiexamples_tpu.train.trainer import TrainConfig


def format_pubmedqa(row: Dict) -> SFTExample:
    """{'QUESTION','CONTEXTS',...,'LONG_ANSWER'} → prompt/completion."""
    contexts = "\n".join(row.get("CONTEXTS", []))
    prompt = (f"Context: {contexts}\nQuestion: {row['QUESTION']}\n"
              f"Answer: ")
    return SFTExample(prompt=prompt, completion=row["LONG_ANSWER"])


def format_alpaca(row: Dict) -> SFTExample:
    """{'instruction','input','output'} instruction-tuning rows."""
    inp = row.get("input", "")
    prompt = (f"Instruction: {row['instruction']}\n"
              + (f"Input: {inp}\n" if inp else "") + "Response: ")
    return SFTExample(prompt=prompt, completion=row["output"])


RECIPES: Dict[str, TrainConfig] = {
    # Gemma/lora.ipynb cell 26-28: LoRA on attention, mbs 1 / gbs 8, 50 steps
    "lora_pubmedqa": TrainConfig(
        mode="lora", lora=LoraConfig(rank=8, alpha=16.0),
        micro_batch_size=1, global_batch_size=8, max_steps=50,
        learning_rate=1e-4, seq_len=1024, steps_per_dispatch=5),
    # Gemma/sft.ipynb: full-parameter SFT (multi-chip FSDP)
    "sft_full": TrainConfig(
        mode="full", micro_batch_size=1, global_batch_size=8, max_steps=50,
        learning_rate=5e-6, seq_len=1024),
    # StarCoder2/lora.ipynb: code LoRA (longer sequences)
    "lora_code": TrainConfig(
        mode="lora", lora=LoraConfig(rank=16, alpha=32.0,
                                     targets=("wq", "wk", "wv", "wo",
                                              "w_gate", "w_up", "w_down")),
        micro_batch_size=1, global_batch_size=8, max_steps=50,
        learning_rate=2e-4, seq_len=2048),
    # StarCoder2/lora.ipynb with the actual starcoder2 architecture
    # (--model starcoder2-3b / tiny-starcoder2): plain-MLP targets only
    "lora_starcoder2": TrainConfig(
        mode="lora", lora=LoraConfig(rank=16, alpha=32.0,
                                     targets=("wq", "wk", "wv", "wo",
                                              "w_up", "w_down")),
        micro_batch_size=1, global_batch_size=8, max_steps=50,
        learning_rate=2e-4, seq_len=2048),
    # finetuning/NeMo/slm: small-LM pretraining from scratch (full params,
    # higher LR, longer schedule) then SFT via the other recipes
    "slm_pretrain": TrainConfig(
        mode="full", micro_batch_size=4, global_batch_size=32,
        max_steps=1000, warmup_steps=100, learning_rate=3e-4, seq_len=1024,
        steps_per_dispatch=8, checkpoint_every=200),
    # test/demo-scale recipe (the suite's fast path)
    "demo": TrainConfig(
        mode="lora", lora=LoraConfig(rank=4, alpha=8.0),
        micro_batch_size=2, global_batch_size=4, max_steps=10,
        warmup_steps=2, seq_len=64, log_every=1),
}

FORMATTERS: Dict[str, Callable[[Dict], SFTExample]] = {
    "pubmedqa": format_pubmedqa,
    "alpaca": format_alpaca,
}


def get_recipe(name: str) -> TrainConfig:
    if name not in RECIPES:
        raise KeyError(f"unknown recipe {name!r}; have {sorted(RECIPES)}")
    return RECIPES[name]
