"""CLI fine-tuning driver: `python -m generativeaiexamples_tpu.train`.

One-command replacement for the reference's notebook+container recipes
(ref: finetuning/Gemma/README.md — pull nvcr nemo image, run lora.ipynb):

    python -m generativeaiexamples_tpu.train \
        --recipe lora_pubmedqa --data train.jsonl \
        --init-checkpoint ckpts/base --checkpoint-dir runs/lora1 --merge

Loads a recipe preset (train/recipes.py), streams jsonl SFT data, trains on
the local mesh, and optionally writes merged serving-ready params (the
reference's merge_lora_weights step, Gemma/lora.ipynb cell 48).
"""

from __future__ import annotations

import argparse
import dataclasses
import logging

import jax

from generativeaiexamples_tpu.models import llama, model_configs
from generativeaiexamples_tpu.train import checkpoints, data as data_lib, recipes
from generativeaiexamples_tpu.train.trainer import Trainer

log = logging.getLogger(__name__)

MODEL_CONFIGS = model_configs()


def main(argv=None) -> None:
    from generativeaiexamples_tpu.core.debug import install as _debug_install
    _debug_install()
    ap = argparse.ArgumentParser("generativeaiexamples_tpu.train")
    ap.add_argument("--recipe", default="lora_pubmedqa",
                    choices=sorted(recipes.RECIPES))
    ap.add_argument("--model", default="tiny", choices=sorted(MODEL_CONFIGS))
    ap.add_argument("--data", required=True, help="jsonl with prompt/completion")
    ap.add_argument("--format", default="", choices=["", *sorted(recipes.FORMATTERS)],
                    help="convert raw dataset rows (e.g. pubmedqa) to prompt/completion")
    ap.add_argument("--tokenizer", default="", help="HF tokenizer dir (default: byte)")
    ap.add_argument("--init-checkpoint", default="",
                    help="orbax params dir (default: random init)")
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--max-steps", type=int, default=0, help="override recipe")
    ap.add_argument("--merge", action="store_true",
                    help="write merged serving params to <checkpoint-dir>/merged")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO, force=True)
    model_cfg = MODEL_CONFIGS[args.model]()
    tcfg = recipes.get_recipe(args.recipe)
    overrides = {"checkpoint_dir": args.checkpoint_dir}
    if args.max_steps:
        overrides["max_steps"] = args.max_steps
    tcfg = dataclasses.replace(tcfg, **overrides)

    if args.init_checkpoint:
        params = checkpoints.load_params(args.init_checkpoint, model_cfg)
    else:
        log.info("no --init-checkpoint: random init (%s)", args.model)
        params = llama.init_params(jax.random.PRNGKey(0), model_cfg)

    from generativeaiexamples_tpu.engine.tokenizer import get_tokenizer
    tok = get_tokenizer(args.tokenizer)
    if args.format:
        examples = data_lib.load_jsonl_with(args.data, recipes.FORMATTERS[args.format])
    else:
        examples = data_lib.load_jsonl(args.data)
    if not examples:
        raise SystemExit(f"no training examples in {args.data}")
    log.info("loaded %d examples from %s", len(examples), args.data)
    # wrap-fill so datasets smaller than a global batch still train
    stream = data_lib.batches(
        examples, tok.encode, batch_size=tcfg.global_batch_size,
        seq_len=tcfg.seq_len, epochs=10_000,  # trainer stops at max_steps
        drop_remainder=False)

    trainer = Trainer(model_cfg, tcfg, params)
    if args.resume and args.checkpoint_dir:
        trainer.restore(args.checkpoint_dir)
        log.info("resumed at step %d", trainer.step)

    def on_step(step, m):
        if step % tcfg.log_every == 0 or step == tcfg.max_steps:
            log.info("step %d loss %.4f grad_norm %.3f tok/s/chip %.1f",
                     step, m["loss"], m["grad_norm"],
                     m["tokens_per_s_per_chip"])

    final = trainer.fit(stream, on_step=on_step)
    log.info("done at step %d: %s", trainer.step, final)

    if args.merge and args.checkpoint_dir:
        merged_dir = f"{args.checkpoint_dir}/merged"
        checkpoints.save_params(merged_dir, trainer.merged_params())
        log.info("merged serving params → %s", merged_dir)


if __name__ == "__main__":
    main()
