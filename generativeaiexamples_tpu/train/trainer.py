"""SFT / LoRA trainer: pjit DP/FSDP(+TP) over an ICI mesh.

In-tree replacement for the reference's notebook-driven NeMo/Megatron path
(ref: finetuning/Gemma/lora.ipynb cells 26-39 — TP/PP/micro/global batch
knobs into `MegatronLMPPTrainerBuilder`, `MegatronGPTSFTModel.restore_from`,
`add_adapter(LoraPEFTConfig)`, `trainer.fit`; executed by an external
container over NCCL). Here the same recipe is one process:

  * parallelism = sharding rules over a `jax.sharding.Mesh` (data/fsdp/
    tensor axes); XLA inserts the gradient all-reduces the NCCL world did;
  * micro/global batch = `accum` microbatch scan inside one jitted step
    (grads averaged on device, no host round-trips);
  * LoRA = optimizer state over the adapter pytree only, base params are
    frozen donated buffers; full SFT = same step with the roles collapsed;
  * checkpoints/resume via orbax (train/checkpoints.py), replacing NeMo's
    `exp_manager` .nemo archives (ref: lora.ipynb cell 30).

Metrics reported per step: loss, grad-norm, tokens/s and tokens/s/chip —
the BASELINE.json LoRA north star.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.parallel import mesh as pmesh
from generativeaiexamples_tpu.parallel import sharding as psh
from generativeaiexamples_tpu.train import checkpoints
from generativeaiexamples_tpu.train import lora as lora_lib

Params = Dict[str, Any]


@dataclass(frozen=True)
class TrainConfig:
    """Trainer knobs; names mirror the reference's hydra overrides
    (micro_batch_size/global_batch_size/max_steps, lora.ipynb cells 26-28)."""

    mode: str = "lora"                     # "lora" | "full"
    lora: lora_lib.LoraConfig = field(default_factory=lora_lib.LoraConfig)
    seq_len: int = 512
    micro_batch_size: int = 1
    global_batch_size: int = 8
    max_steps: int = 50
    learning_rate: float = 1e-4
    weight_decay: float = 0.01
    warmup_steps: int = 10
    grad_clip_norm: float = 1.0
    checkpoint_dir: str = ""
    checkpoint_every: int = 0              # 0 = only at end
    log_every: int = 10
    # Steps dispatched ahead of host-side loss resolution. The device serial-
    # izes steps anyway (step k+1 consumes step k's donated state), so running
    # the host ahead only overlaps the per-step device→host loss fetch — which
    # over a tunneled chip costs ~a serialized RTT per sync — with compute.
    # 0 = resolve every step synchronously (the pre-round-4 behavior, kept for
    # the loss-parity test and debugging).
    dispatch_ahead: int = 4
    # Optimizer steps fused into one jitted dispatch (lax.scan over stacked
    # batches) — the trainer's analogue of the serving engine's
    # decode_steps_per_dispatch. Measured on the tunneled v5e: a real train
    # step costs ~1 s of per-dispatch overhead regardless of batch size
    # (arg marshaling across the tunnel), so fusing 8 steps amortizes it 8x.
    # Checkpoints land on dispatch-group boundaries when >1.
    steps_per_dispatch: int = 1
    # Rematerialization policy for the layer scan (llama.REMAT_POLICIES key,
    # "" = save everything). "dots" keeps matmul outputs and recomputes
    # elementwise ops in the backward — ~zero extra FLOPs but roughly halves
    # activation memory, which is what bounds the microbatch on one chip.
    remat: str = "dots"

    @property
    def accum(self) -> int:
        if self.global_batch_size % self.micro_batch_size:
            raise ValueError("global_batch_size must divide by micro_batch_size")
        return self.global_batch_size // self.micro_batch_size


MOE_AUX_WEIGHT = 0.01   # Switch-style load-balance loss coefficient


def causal_lm_loss(model_cfg: llama.LlamaConfig, params: Params,
                   tokens: jnp.ndarray, loss_mask: jnp.ndarray,
                   adapters: Optional[Params] = None,
                   remat: Optional[str] = None) -> jnp.ndarray:
    """Masked next-token cross-entropy. tokens/loss_mask: (B, S+1); loss over
    predicting tokens[:,1:] from tokens[:,:-1], masked by loss_mask[:,1:].
    MoE models add the router load-balance auxiliary loss."""
    aux = 0.0
    if model_cfg.mlp == "moe":
        logits, aux = llama.forward(params, model_cfg, tokens[:, :-1],
                                    adapters=adapters, return_aux=True,
                                    remat=remat)
    else:
        logits = llama.forward(params, model_cfg, tokens[:, :-1],
                               adapters=adapters, remat=remat)
    targets = tokens[:, 1:]
    mask = loss_mask[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return ((nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
            + MOE_AUX_WEIGHT * aux)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=cfg.learning_rate,
        warmup_steps=max(cfg.warmup_steps, 1),
        decay_steps=max(cfg.max_steps, cfg.warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.adamw(schedule, weight_decay=cfg.weight_decay))


class Trainer:
    """Drives the jitted train step over a mesh; owns state + checkpoints.

    `trainable` is the adapter pytree in LoRA mode (base `params` frozen) or
    the full param tree in full-SFT mode (`params` then aliases it).
    """

    def __init__(self, model_cfg: llama.LlamaConfig, cfg: TrainConfig,
                 params: Params, mesh: Optional[Mesh] = None,
                 rng: Optional[jax.Array] = None):
        self.model_cfg, self.cfg = model_cfg, cfg
        self.mesh = mesh or pmesh.create_mesh(
            pmesh.MeshConfig(axes=pmesh.TRAIN_AXES))
        self.opt = make_optimizer(cfg)
        self.step = 0

        rules = psh.TRAIN_RULES
        self.params = psh.shard_params(
            params, llama.logical_axes(model_cfg), rules, self.mesh)
        if cfg.mode == "lora":
            adapters = lora_lib.init_adapters(
                rng if rng is not None else jax.random.PRNGKey(0),
                model_cfg, cfg.lora)
            self.trainable = psh.shard_params(
                adapters, lora_lib.adapter_logical_axes(cfg.lora), rules,
                self.mesh)
        elif cfg.mode == "full":
            # The train step donates the trainable buffers; device_put may
            # have aliased the caller's arrays, so copy (a non-donated jit
            # cannot alias inputs into outputs) to avoid deleting them.
            self.params = jax.jit(lambda t: jax.tree.map(jnp.copy, t))(
                self.params)
            self.trainable = self.params
        else:
            raise ValueError(f"unknown mode {cfg.mode!r}")
        self.opt_state = jax.jit(self.opt.init)(self.trainable)
        self._train_step = self._build_step()

    # -- jitted step -------------------------------------------------------
    def _build_step(self):
        cfg, model_cfg, opt = self.cfg, self.model_cfg, self.opt
        is_lora = cfg.mode == "lora"
        # shard the microbatch over the dp axes when it divides evenly,
        # otherwise replicate (tiny test batches)
        dp = self.mesh.shape.get("data", 1) * self.mesh.shape.get("fsdp", 1)
        batch_ax = ("data", "fsdp") if cfg.micro_batch_size % dp == 0 else None
        # (K, accum, mbs, S+1): steps and microbatches replicated in time,
        # the microbatch row sharded over the dp axes
        batch_spec = NamedSharding(self.mesh, P(None, None, batch_ax, None))

        remat = cfg.remat or None

        def loss_fn(trainable, params, tokens, loss_mask):
            adapters = trainable if is_lora else None
            p = params if is_lora else trainable
            return causal_lm_loss(model_cfg, p, tokens, loss_mask, adapters,
                                  remat=remat)

        def step_fn(trainable, opt_state, params, tokens, loss_mask):
            # microbatch scan: (accum, mbs, S+1) → averaged grads on device
            def micro(carry, xs):
                loss_acc, grad_acc = carry
                t, m = xs
                loss, grads = jax.value_and_grad(loss_fn)(trainable, params, t, m)
                return (loss_acc + loss,
                        jax.tree.map(jnp.add, grad_acc, grads)), None

            zero = jax.tree.map(jnp.zeros_like, trainable)
            (loss_sum, grad_sum), _ = jax.lax.scan(
                micro, (jnp.zeros((), jnp.float32), zero), (tokens, loss_mask))
            inv = 1.0 / tokens.shape[0]
            grads = jax.tree.map(lambda g: g * inv, grad_sum)
            gnorm = optax.global_norm(grads)
            updates, opt_state = opt.update(grads, opt_state, trainable)
            trainable = optax.apply_updates(trainable, updates)
            return trainable, opt_state, loss_sum * inv, gnorm

        def multi_fn(trainable, opt_state, params, tokens, loss_mask):
            # K optimizer steps per dispatch: tokens (K, accum, mbs, S+1).
            # One compiled program per distinct K; losses/gnorms come back
            # stacked (K,) so fit() can still report per-step metrics.
            def one(carry, xs):
                tr, os = carry
                t, m = xs
                tr, os, loss, gnorm = step_fn(tr, os, params, t, m)
                return (tr, os), (loss, gnorm)

            (trainable, opt_state), (losses, gnorms) = jax.lax.scan(
                one, (trainable, opt_state), (tokens, loss_mask))
            return trainable, opt_state, losses, gnorms

        jitted = jax.jit(multi_fn, donate_argnums=(0, 1))
        self._batch_spec = batch_spec

        def run(trainable, opt_state, params, tokens, mask):
            # full mode: params is an alias of trainable, which is donated —
            # pass an empty tree instead of aliasing a donated buffer
            return jitted(trainable, opt_state, params if is_lora else {},
                          tokens, mask)

        return run

    def _stage_group(self, group) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], int]:
        """Stack K host batches and issue ONE host→device transfer (async;
        overlaps the in-flight dispatch's compute). Returns device arrays
        shaped (K, accum, mbs, S+1) + total token count."""
        import numpy as np

        accum, mbs = self.cfg.accum, self.cfg.micro_batch_size
        tokens = np.stack([b.tokens.reshape(accum, mbs, -1) for b in group])
        mask = np.stack([b.loss_mask.reshape(accum, mbs, -1) for b in group])
        return ((jax.device_put(tokens, self._batch_spec),
                 jax.device_put(mask, self._batch_spec)),
                int(tokens.size))

    # -- loop --------------------------------------------------------------
    def fit(self, data: Iterable[Any],
            on_step: Optional[Callable[[int, Dict[str, float]], None]] = None
            ) -> Dict[str, float]:
        """Pipelined train loop. Dispatch runs up to `cfg.dispatch_ahead`
        steps ahead of host-side loss resolution: the device already
        serializes steps (each consumes the previous step's donated state),
        so blocking the host per step only adds a device→host fetch RTT to
        every step — ruinous over a tunneled chip. Inputs for the *next*
        step are staged (async device_put) right after the current dispatch
        so the transfer rides under compute. Reported tokens/s is cumulative
        host-observed tokens over wall time — resolving step k's loss proves
        steps 1..k completed (the donation chain), so nothing async can
        inflate it."""
        cfg = self.cfg
        n_chips = self.mesh.devices.size
        spd = max(cfg.steps_per_dispatch, 1)
        last: Dict[str, float] = {}
        pending: list = []        # (first_step, k, losses(K,), gnorms(K,), toks)
        t_start = time.perf_counter()
        toks_resolved = 0
        fit_first_step = self.step

        def pending_steps() -> int:
            return sum(k for _, k, _, _, _ in pending)

        def resolve_one() -> None:
            nonlocal last, toks_resolved, t_start
            first_step, k, losses, gnorms, toks = pending.pop(0)
            # ONE device→host transfer for the whole dispatch (per-scalar
            # float() would pay a serialized tunnel RTT per value)
            # tpulint: disable=devtime-fence -- training loop, not serving:
            # the deliberate once-per-dispatch metrics fetch documented above
            losses, gnorms = jax.device_get((losses, gnorms))
            losses, gnorms = [float(x) for x in losses], [float(x) for x in gnorms]
            toks_resolved += toks
            wall = time.perf_counter() - t_start
            rate = toks_resolved / max(wall, 1e-9)
            if first_step == fit_first_step + 1:
                # first dispatch of this fit() absorbs XLA compile: restart
                # the rate baseline so steady-state tokens/s isn't diluted
                t_start = time.perf_counter()
                toks_resolved = 0
            for i in range(k):
                last = {"loss": losses[i], "grad_norm": gnorms[i],
                        "tokens_per_s": rate,
                        "tokens_per_s_per_chip": rate / n_chips}
                REGISTRY.histogram("train.loss").observe(losses[i])
                REGISTRY.histogram("train.tokens_per_s_per_chip").observe(
                    last["tokens_per_s_per_chip"])
                if on_step:
                    on_step(first_step + i, last)

        it = iter(data)

        def next_group():
            """Pull up to spd host batches, bounded by remaining steps
            (counting work already dispatched but not yet resolved)."""
            room = cfg.max_steps - self.step
            group = []
            while len(group) < min(spd, room):
                batch = next(it, None)
                if batch is None:
                    break
                group.append(batch)
            return self._stage_group(group) if group else None

        staged = next_group()        # device-resident inputs for next dispatch
        while staged is not None:
            (tokens, mask), toks = staged
            k = tokens.shape[0]
            self.trainable, self.opt_state, losses, gnorms = self._train_step(
                self.trainable, self.opt_state, self.params, tokens, mask)
            if cfg.mode == "full":
                self.params = self.trainable
            first = self.step + 1
            self.step += k
            pending.append((first, k, losses, gnorms, toks))
            # stage the next group now: its transfer overlaps this dispatch
            staged = next_group()
            # ahead=0 = fully synchronous (parity/debug); otherwise never
            # force-resolve the dispatch just issued — a fused group larger
            # than dispatch_ahead would otherwise sync every dispatch
            ahead = 0 if cfg.dispatch_ahead == 0 else max(cfg.dispatch_ahead,
                                                          spd)
            while pending_steps() > ahead:
                resolve_one()
            if (cfg.checkpoint_dir and cfg.checkpoint_every
                    and (self.step // cfg.checkpoint_every
                         > (self.step - k) // cfg.checkpoint_every)):
                while pending:      # checkpoint metrics/state in step order
                    resolve_one()
                self.save(cfg.checkpoint_dir)
        while pending:
            resolve_one()
        if cfg.checkpoint_dir:
            self.save(cfg.checkpoint_dir)
        return last

    # -- checkpoint / resume (SURVEY §5.4) ---------------------------------
    def save(self, directory: str) -> None:
        checkpoints.save_train_state(
            directory, step=self.step, trainable=self.trainable,
            opt_state=self.opt_state)

    def restore(self, directory: str) -> None:
        # orbax restores onto committed single-device arrays for leaves whose
        # template was an uncommitted scalar (opt.init's count); committed
        # single-device + mesh-sharded can't mix in one jitted step, so
        # re-place every leaf: keep mesh shardings, replicate the rest.
        replicated = NamedSharding(self.mesh, P())

        def live_sharding(x):
            s = x.sharding
            return s if isinstance(s, NamedSharding) else replicated

        t_sh = jax.tree.map(live_sharding, self.trainable)
        o_sh = jax.tree.map(live_sharding, self.opt_state)
        self.step, trainable, opt_state = checkpoints.load_train_state(
            directory, trainable=self.trainable, opt_state=self.opt_state)
        self.trainable = jax.tree.map(jax.device_put, trainable, t_sh)
        self.opt_state = jax.tree.map(jax.device_put, opt_state, o_sh)
        if self.cfg.mode == "full":
            self.params = self.trainable

    def merged_params(self) -> Params:
        """Base params with adapters folded in (serving-ready); full mode
        returns the trained params unchanged."""
        if self.cfg.mode == "lora":
            return lora_lib.merge_adapters(self.params, self.trainable)
        return self.params
