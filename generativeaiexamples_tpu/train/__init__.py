"""Training: LoRA / SFT trainers, orbax checkpointing, data pipelines.

Replaces the reference's notebook-driven NeMo/Megatron fine-tuning containers
(ref: finetuning/Gemma/lora.ipynb, SURVEY §2.4) with in-tree JAX trainers:
DP/FSDP(+TP) via pjit sharding over ICI, XLA collectives instead of NCCL,
orbax sharded checkpoints instead of `.nemo` files.
"""
