"""Tool-calling fine-tuning flywheel: synthesize traces → LoRA → measure.

Capability parity with the reference's tool-calling data flywheel
(ref: nemo/data-flywheel/tool-calling/*.ipynb — harvest/synthesize
tool-call conversations, fine-tune with the NeMo Customizer, score
function-name and argument accuracy with the Evaluator service). Here the
whole loop is in-tree and TPU-native, mirroring the embedder flywheel
(train/embedder_ft.py): synthesize → LoRA with the existing trainer →
call-accuracy before/after as a printed fact.

Traces use exactly the serving-side tool contract (engine/tools.py renders
the prompt; parse_tool_calls scores the output), so a model tuned here is
tuned for what `/v1/chat/completions` will actually ask of it —
train/serve symmetry, the same property the embedder flywheel keeps with
its QUERY_PREFIX/PASSAGE_PREFIX.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from generativeaiexamples_tpu.engine import tools as tools_mod
from generativeaiexamples_tpu.train.data import Batch

logger = logging.getLogger(__name__)

# A compact tool catalog with templated invocations: enough surface (string
# / number / enum args, multi-arg calls, no-tool distractors) to teach and
# to measure the contract.
CATALOG: List[Dict[str, Any]] = [
    {"spec": {"type": "function", "function": {
        "name": "get_weather",
        "description": "Current weather for a city.",
        "parameters": {"type": "object", "properties": {
            "city": {"type": "string"}}, "required": ["city"]}}},
     "queries": [("What's the weather in {city}?", {"city": ["Oslo", "Lima",
                  "Osaka", "Quito", "Turin", "Perth", "Dakar", "Hanoi"]}),
                 ("Is it raining in {city} right now?", {"city": ["Bergen",
                  "Seattle", "Mumbai", "Leeds"]})]},
    {"spec": {"type": "function", "function": {
        "name": "calculator",
        "description": "Evaluate an arithmetic expression.",
        "parameters": {"type": "object", "properties": {
            "expression": {"type": "string"}}, "required": ["expression"]}}},
     "queries": [("What is {a} times {b}?", {"a": ["12", "7", "31", "54"],
                                             "b": ["9", "17", "23", "3"]},
                  lambda v: {"expression": f"{v['a']}*{v['b']}"}),
                 ("Compute {a} plus {b}.", {"a": ["101", "44"],
                                            "b": ["76", "19"]},
                  lambda v: {"expression": f"{v['a']}+{v['b']}"})]},
    {"spec": {"type": "function", "function": {
        "name": "search_documents",
        "description": "Search the knowledge base.",
        "parameters": {"type": "object", "properties": {
            "query": {"type": "string"},
            "top_k": {"type": "integer"}}, "required": ["query"]}}},
     "queries": [("Find docs about {topic}.", {"topic": ["pump torque",
                  "ICI wiring", "coolant specs", "safety interlocks"]},
                  lambda v: {"query": v["topic"], "top_k": 4})]},
]

# no-tool distractors: the model must answer in plain text
PLAIN_QUERIES = [
    ("Say hello.", "Hello!"),
    ("What does TPU stand for?", "Tensor Processing Unit."),
    ("Thanks for the help!", "You're welcome!"),
    ("Write the word 'ready'.", "ready"),
]


def catalog_specs(catalog: Sequence[Dict] = CATALOG) -> List[Dict]:
    return [entry["spec"] for entry in catalog]


def generate_traces(n: int, seed: int = 0,
                    catalog: Sequence[Dict] = CATALOG,
                    plain_fraction: float = 0.25) -> List[Dict[str, Any]]:
    """Synthesize tool-call conversations.

    Each trace: {"query", "tool" (name or None), "arguments", "target"}
    where target is the canonical assistant output under the serving
    contract — the {"tool_calls": [...]} JSON, or the plain answer."""
    rng = random.Random(seed)
    traces: List[Dict[str, Any]] = []
    for _ in range(n):
        if rng.random() < plain_fraction:
            query, answer = rng.choice(PLAIN_QUERIES)
            traces.append({"query": query, "tool": None, "arguments": None,
                           "target": answer})
            continue
        entry = rng.choice(list(catalog))
        q = rng.choice(entry["queries"])
        template, slots, builder = (q if len(q) == 3 else (*q, None))
        values = {k: rng.choice(v) for k, v in slots.items()}
        args = builder(values) if builder else dict(values)
        name = entry["spec"]["function"]["name"]
        target = json.dumps({"tool_calls": [
            {"name": name, "arguments": args}]})
        traces.append({"query": template.format(**values), "tool": name,
                       "arguments": args, "target": target})
    return traces


# ------------------------------------------------------------------- data

def trace_batches(traces: Sequence[Dict], tokenizer, *, batch_size: int,
                  seq_len: int, epochs: int = 1, seed: int = 0,
                  catalog: Sequence[Dict] = CATALOG) -> Iterator[Batch]:
    """Fixed-shape SFT batches: prompt = the SAME chat template + tool
    system prompt the server renders, completion = the canonical target
    (loss only on the completion + EOS, mirroring train/data.py)."""
    specs = catalog_specs(catalog)
    encoded = []
    dropped = 0
    for t in traces:
        messages = tools_mod.inject_tool_prompt(
            [{"role": "user", "content": t["query"]}], specs, "auto")
        prompt_ids = tokenizer.apply_chat_template(messages)
        comp_ids = tokenizer.encode(t["target"]) + [tokenizer.eos_id]
        ids = (list(prompt_ids) + comp_ids)[: seq_len + 1]
        mask = ([0] * len(prompt_ids) + [1] * len(comp_ids))[: seq_len + 1]
        if not any(mask):
            dropped += 1   # prompt alone filled the window: nothing to learn
            continue
        encoded.append((ids, mask))
    if dropped:
        logger.warning("trace_batches: dropped %d/%d traces whose tool "
                       "prompt left no room for the completion at "
                       "seq_len=%d", dropped, len(traces), seq_len)
    if not encoded:
        raise ValueError(f"every trace's prompt exceeds seq_len={seq_len}; "
                         "raise seq_len or shrink the tool catalog")
    rng = random.Random(seed)
    order = list(range(len(encoded)))
    for _ in range(epochs):
        rng.shuffle(order)
        for i in range(0, len(order), batch_size):
            idx = order[i:i + batch_size]
            while len(idx) < batch_size:      # wrap-fill the tail
                idx = idx + idx[: batch_size - len(idx)]
            tokens = np.zeros((batch_size, seq_len + 1), np.int32)
            mask = np.zeros((batch_size, seq_len + 1), np.float32)
            for r, j in enumerate(idx):
                ids, m = encoded[j]
                tokens[r, :len(ids)] = ids
                mask[r, :len(m)] = m
            yield Batch(tokens=tokens, loss_mask=mask)


# ------------------------------------------------------------------- eval

def call_accuracy(generate: Callable[[List[Dict]], str],
                  traces: Sequence[Dict],
                  catalog: Sequence[Dict] = CATALOG) -> float:
    """Fraction of traces where the model's output parses to EXACTLY the
    expected call (function name AND arguments; for no-tool traces, to no
    call at all) — the Evaluator-service scoring of the reference flywheel
    reduced to its two hard criteria."""
    if not traces:
        return 0.0
    specs = catalog_specs(catalog)
    hits = 0
    for t in traces:
        messages = tools_mod.inject_tool_prompt(
            [{"role": "user", "content": t["query"]}], specs, "auto")
        text = generate(messages)
        calls = tools_mod.parse_tool_calls(text, specs)
        if t["tool"] is None:
            hits += calls is None
            continue
        if not calls or len(calls) != 1:
            continue
        fn = calls[0]["function"]
        if (fn["name"] == t["tool"]
                and json.loads(fn["arguments"]) == t["arguments"]):
            hits += 1
    return hits / len(traces)


def scheduler_generate(scheduler, max_tokens: int = 96
                       ) -> Callable[[List[Dict]], str]:
    """A `generate` callable over the serving scheduler (greedy)."""
    def gen(messages: List[Dict]) -> str:
        ids = scheduler.tokenizer.apply_chat_template(messages)
        return scheduler.generate(ids, max_tokens=max_tokens,
                                  temperature=0.0)
    return gen


# ---------------------------------------------------------------- flywheel

@dataclasses.dataclass(frozen=True)
class ToolcallFTConfig:
    n_train: int = 256
    n_eval: int = 64
    seq_len: int = 768      # must hold the rendered tool prompt + target
    batch_size: int = 8
    epochs: int = 4
    lora_rank: int = 8
    learning_rate: float = 1e-4
    seed: int = 0


def run_flywheel(model_cfg, params, tokenizer,
                 cfg: ToolcallFTConfig = ToolcallFTConfig(),
                 eval_generate: Optional[Callable] = None,
                 catalog: Sequence[Dict] = CATALOG) -> Dict[str, Any]:
    """The full loop: synthesize → LoRA-tune → merge → score before/after.

    Returns {"losses", "accuracy_before", "accuracy_after",
    "merged_params"}. ``eval_generate(params) -> generate-callable`` lets
    callers choose the eval harness (default: a fresh tiny serving
    scheduler per side, greedy)."""
    import jax

    from generativeaiexamples_tpu.train.lora import LoraConfig
    from generativeaiexamples_tpu.train.trainer import TrainConfig, Trainer

    train = generate_traces(cfg.n_train, seed=cfg.seed, catalog=catalog)
    heldout = generate_traces(cfg.n_eval, seed=cfg.seed + 1, catalog=catalog)

    def _measure(p) -> float:
        if eval_generate is not None:
            return call_accuracy(eval_generate(p), heldout, catalog=catalog)
        # default harness: a throwaway serving scheduler per side, STOPPED
        # after scoring (its KV pool + driver thread must not outlive the
        # measurement — two leaked pools per flywheel run would eventually
        # OOM the chip)
        from generativeaiexamples_tpu.core.config import EngineConfig
        from generativeaiexamples_tpu.engine.engine import EngineCore
        from generativeaiexamples_tpu.engine.scheduler import Scheduler
        core = EngineCore(model_cfg,
                          EngineConfig(max_batch_size=4,
                                       max_seq_len=cfg.seq_len + 128,
                                       page_size=16, prefill_chunk=64),
                          jax.tree.map(lambda x: x, p),
                          eos_id=tokenizer.eos_id)
        sched = Scheduler(core, tokenizer)
        sched.start()
        try:
            return call_accuracy(scheduler_generate(sched), heldout,
                                 catalog=catalog)
        finally:
            sched.stop()

    acc_before = _measure(params)

    tcfg = TrainConfig(mode="lora", lora=LoraConfig(rank=cfg.lora_rank),
                       micro_batch_size=cfg.batch_size,
                       global_batch_size=cfg.batch_size,
                       max_steps=10**9, warmup_steps=8,
                       seq_len=cfg.seq_len,
                       learning_rate=cfg.learning_rate)
    trainer = Trainer(model_cfg, tcfg, params)
    losses: List[float] = []
    trainer.fit(trace_batches(
        train, tokenizer, batch_size=cfg.batch_size, seq_len=cfg.seq_len,
        epochs=cfg.epochs, seed=cfg.seed, catalog=catalog),
        on_step=lambda _i, m: losses.append(m["loss"]))
    merged = trainer.merged_params()
    acc_after = _measure(merged)
    logger.info("tool-call flywheel: accuracy %.3f -> %.3f (loss %.3f -> "
                "%.3f)", acc_before, acc_after,
                losses[0] if losses else 0.0,
                losses[-1] if losses else 0.0)
    return {"losses": losses, "accuracy_before": acc_before,
            "accuracy_after": acc_after, "merged_params": merged}
