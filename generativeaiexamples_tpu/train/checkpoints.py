"""Orbax checkpoint save/load for model parameter pytrees.

The TPU-native replacement for the reference's two checkpoint stories
(SURVEY §5.4): NeMo `.nemo` archives written by `exp_manager`
(ref: finetuning/Gemma/lora.ipynb cell 30) and the NIM model cache volume
(ref: docker-compose-nim-ms.yaml:6-7). Checkpoints are sharded + async-able
via orbax; serving (`engine/__main__.py`) and the trainer share this module.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from generativeaiexamples_tpu.models import llama

PARAMS_SUBDIR = "params"
TRAIN_STATE_SUBDIR = "train_state"


def save_params(directory: str, params: Any) -> None:
    """Write a parameter pytree to ``directory``/params (overwrites)."""
    path = os.path.abspath(os.path.join(directory, PARAMS_SUBDIR))
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()


def load_params(directory: str, model_cfg: llama.LlamaConfig,
                target: Optional[Any] = None) -> Any:
    """Restore a parameter pytree; shape/dtype template comes from the model
    config unless an explicit ``target`` (e.g. sharded abstract tree) is given."""
    path = os.path.abspath(os.path.join(directory, PARAMS_SUBDIR))
    if target is None:
        target = jax.eval_shape(
            lambda: llama.init_params(jax.random.PRNGKey(0), model_cfg))
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, target)


def save_train_state(directory: str, *, step: int, trainable: Any,
                     opt_state: Any) -> None:
    """Write trainer state (step + trainable params + optimizer state) for
    resume — the orbax replacement for NeMo's `exp_manager` .nemo archives
    (ref: finetuning/Gemma/lora.ipynb cell 30)."""
    import jax.numpy as jnp

    path = os.path.abspath(os.path.join(directory, TRAIN_STATE_SUBDIR))
    tree = {"step": jnp.asarray(step, jnp.int32), "trainable": trainable,
            "opt_state": opt_state}
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, tree, force=True)
    ckptr.wait_until_finished()


def load_train_state(directory: str, *, trainable: Any, opt_state: Any):
    """Restore (step, trainable, opt_state); current values are the
    shape/dtype/sharding template."""
    import jax.numpy as jnp

    path = os.path.abspath(os.path.join(directory, TRAIN_STATE_SUBDIR))
    target = {"step": jnp.asarray(0, jnp.int32), "trainable": trainable,
              "opt_state": opt_state}
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path, target)
    return int(restored["step"]), restored["trainable"], restored["opt_state"]
