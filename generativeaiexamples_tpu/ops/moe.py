"""Mixture-of-experts MLP with expert parallelism (the mesh's "expert" axis).

The reference exposes MoE only as hosted Mixtral endpoints (no in-tree MoE
code anywhere); the NeMo knob surface it ships stops at TP/PP
(ref finetuning/Gemma/lora.ipynb cell 26). This module supplies the
TPU-first counterpart so the framework's parallelism story covers
dp/fsdp/tp/sp/ep: GShard/Switch-style top-k routing expressed entirely as
einsums over a dispatch tensor, with the expert dimension sharded over the
mesh's "expert" axis — XLA inserts the all_to_all-equivalent collectives
from the shardings, per the scaling-book recipe (annotate, don't
hand-schedule).

Shapes (N = B*S tokens, E experts, C capacity, D model, F hidden):

    router logits  (N, E)   = x @ w_router
    top-k gates    (N, E)   renormalized over the chosen experts
    dispatch       (N, E, C) one-hot (token n -> slot c of expert e)
    expert input   (E, C, D) = einsum('nec,nd->ecd', dispatch, x)
    expert MLP     (E, C, D) -> (E, C, D) (per-expert w_up/w_down, GLU)
    combine        (N, D)   = einsum('nec,ecd->nd', dispatch*gates, out)

Tokens beyond an expert's capacity are dropped for that expert (classic
Switch semantics) — the residual connection carries them through, and the
load-balance auxiliary loss (Switch Eq.4: E * sum_i fraction_i ·
mean_router_prob_i, ~1.0 at uniform routing) pushes the router toward
uniform load so drops stay rare. ``capacity_factor`` trades padding FLOPs
for drop rate.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.ops.layers import glu

Params = Dict[str, Any]


def init_moe_params(rng: jax.Array, dim: int, hidden_dim: int,
                    n_experts: int, dtype=jnp.float32) -> Params:
    """Router + per-expert GLU MLP weights (leading expert axis)."""
    import math

    k1, k2, k3, k4 = jax.random.split(rng, 4)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dtype)

    return {
        "w_router": normal(k1, (dim, n_experts), dim),
        "w_gate": normal(k2, (n_experts, dim, hidden_dim), dim),
        "w_up": normal(k3, (n_experts, dim, hidden_dim), dim),
        "w_down": normal(k4, (n_experts, hidden_dim, dim), hidden_dim),
    }


def moe_logical_axes() -> Params:
    """Sharding annotations: experts over the "expert" axis, hidden over
    "mlp" (composable with TP inside each expert)."""
    return {
        "w_router": ("embed", None),
        "w_gate": ("expert", "embed", "mlp"),
        "w_up": ("expert", "embed", "mlp"),
        "w_down": ("expert", "mlp", "embed"),
    }


def capacity(n_tokens: int, n_experts: int, k: int,
             capacity_factor: float) -> int:
    """Per-expert token slots; multiple of 8 keeps the (E, C, D) blocks
    MXU-tileable."""
    c = int(capacity_factor * k * n_tokens / n_experts) + 1
    return max(8, -(-c // 8) * 8)


def moe_mlp(params: Params, x: jnp.ndarray, k: int = 2,
            capacity_factor: float = 1.25, hidden_act: str = "silu",
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE GLU MLP over tokens x (..., D) → (out (..., D), aux_loss scalar).

    All routing/dispatch math is static-shaped (top_k + one_hot + cumsum)
    so the whole block jits once regardless of routing decisions.
    """
    orig_shape = x.shape
    D = orig_shape[-1]
    xf = x.reshape(-1, D)
    N = xf.shape[0]
    E = params["w_router"].shape[-1]
    C = capacity(N, E, k, capacity_factor)

    # --- routing (f32 for a stable softmax) ------------------------------
    logits = xf.astype(jnp.float32) @ params["w_router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                  # (N, E)
    gate_vals, expert_ix = jax.lax.top_k(probs, k)           # (N, k)
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)              # renormalize

    # load-balance aux loss (Switch Eq.4): E * sum_i f_i * P_i, where f_i is
    # the fraction of tokens FIRST-routed to expert i and P_i the mean router
    # prob — ~1.0 at uniform routing regardless of E, so a tuned coefficient
    # transfers across expert counts
    first_choice = jax.nn.one_hot(expert_ix[:, 0], E)        # (N, E)
    aux = E * jnp.sum(first_choice.mean(0) * probs.mean(0))

    # --- dispatch tensor -------------------------------------------------
    # slot of token n in expert e = number of earlier (token, choice) pairs
    # routed to e; priority is (choice rank, token order)
    choice_oh = jax.nn.one_hot(expert_ix, E)                 # (N, k, E)
    flat = choice_oh.transpose(1, 0, 2).reshape(k * N, E)    # rank-major
    pos = jnp.cumsum(flat, axis=0) - flat                    # (kN, E) slots
    pos = pos.reshape(k, N, E).transpose(1, 0, 2)            # (N, k, E)
    slot = (pos * choice_oh).sum(-1).astype(jnp.int32)       # (N, k)
    keep = slot < C                                          # capacity gate
    gate_vals = gate_vals * keep

    # one_hot already zeroes out-of-range (dropped) slots
    slot_oh = jax.nn.one_hot(slot, C)                        # (N, k, C)
    # (N, E, C): token n occupies slot c of expert e
    dispatch = jnp.einsum("nke,nkc->nec", choice_oh, slot_oh)
    combine = jnp.einsum("nke,nkc,nk->nec", choice_oh, slot_oh, gate_vals)

    # --- expert compute --------------------------------------------------
    dt = x.dtype
    expert_in = jnp.einsum("nec,nd->ecd", dispatch.astype(dt), xf)  # (E,C,D)
    gate_h = jnp.einsum("ecd,edf->ecf", expert_in, params["w_gate"].astype(dt))
    up_h = jnp.einsum("ecd,edf->ecf", expert_in, params["w_up"].astype(dt))
    act = glu(gate_h, up_h, hidden_act)
    expert_out = jnp.einsum("ecf,efd->ecd", act, params["w_down"].astype(dt))

    out = jnp.einsum("nec,ecd->nd", combine.astype(jnp.float32),
                     expert_out.astype(jnp.float32))
    return out.reshape(orig_shape).astype(dt), aux
