"""Flash prefill + ragged decode attention as Pallas TPU kernels.

These replace the attention inside the reference's external NIM container
(TRT-LLM fused/paged attention; ref RAG/examples/local_deploy/
docker-compose-nim-ms.yaml:2-28) with TPU-native kernels. Two properties the
plain XLA path cannot express:

  * **Flash prefill** — blockwise online-softmax attention. Scores are never
    materialized at (S, T); each (blk_q, blk_k) tile lives in VMEM only long
    enough to update the running (max, denominator, accumulator). Causally
    dead and beyond-length KV blocks are *clamped in the index map*: Pallas
    skips the DMA when consecutive grid steps map to the same block, so masked
    tiles cost neither HBM bandwidth nor MXU time.
  * **Ragged decode** — decode attention against a fixed-capacity KV cache
    where each slot has a different live length (continuous batching). The
    per-slot length rides in scalar-prefetch SMEM; KV blocks past the length
    are clamped away, so a slot 100 tokens into a 2048-token cache reads ~1/20
    of the cache instead of all of it. This is the decode-bandwidth win that
    determines tokens/s at low occupancy.

Layouts match the model's cache layout (B, T, KV, HD) — no transposes on the
KV cache, which is the large buffer; only Q (small) is reshaped.

GQA is expressed by grouping: Q is viewed as (KV, G, HD) per batch and scores
are batched over KV heads, so K/V tiles are read once per kv-head, not per
q-head.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(n: int, target: int) -> int:
    """Largest power-of-two divisor of n that is <= target (n power-of-two-ish)."""
    b = 1
    while b * 2 <= target and n % (b * 2) == 0:
        b *= 2
    return b


def prefill_supported(seq_len: int, kv_len: int, head_dim: int) -> bool:
    """Shapes the flash prefill kernel handles without padding games:
    both lengths need a power-of-two block divisor >= 8 (engine buckets are
    powers of two, so serving shapes always qualify)."""
    return (_pick_block(seq_len, 256) >= 8
            and _pick_block(kv_len, 256) >= 8
            and head_dim >= 8)


def decode_supported(kv_len: int, head_dim: int) -> bool:
    return _pick_block(kv_len, 512) >= 8 and head_dim >= 8


def paged_decode_supported(page_size: int, head_dim: int) -> bool:
    """The paged kernel DMAs one physical page per grid step; pages are
    power-of-two >= 8 by engine config, so this is about tiny test shapes."""
    return _pick_block(page_size, page_size) == page_size >= 8 and head_dim >= 8


def ragged_paged_supported(page_size: int, head_dim: int,
                           q_block: int = 8) -> bool:
    """Shapes the mixed-phase ragged kernel handles. It DMAs one physical
    page per grid step exactly like its decode special case, so the
    page_size / head_dim limits are BY CONSTRUCTION the same as
    :func:`paged_decode_supported` — the engine's config gate checks both at
    init and refuses to start if they ever diverge (a kernel the chip
    rejects at trace time must fail at engine init, not at first dispatch).
    ``q_block`` (queries per ragged row) only adds a power-of-two row
    granularity on top."""
    return (paged_decode_supported(page_size, head_dim)
            and q_block >= 1 and q_block & (q_block - 1) == 0)


# ---------------------------------------------------------------------------
# Flash prefill
# ---------------------------------------------------------------------------

def _flash_kernel(starts_ref, throughs_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, blk_q: int, blk_k: int,
                  scale: float, causal: bool):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    start = starts_ref[b]
    through = throughs_ref[b]
    lim = _kv_block_limit(start, through, qi, blk_q, blk_k, causal)

    @pl.when(ki <= lim)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)        # (blk_q, HD)
        k = k_ref[0, 0].astype(jnp.float32)        # (blk_k, HD)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_pos = start + qi * blk_q + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0)
        k_pos = ki * blk_k + jax.lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 1)
        mask = k_pos < through
        if causal:
            mask &= k_pos <= q_pos
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _emit():
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


def _kv_block_limit(start, through, qi, blk_q: int, blk_k: int, causal: bool):
    """Index of the last KV block this q block needs (everything later is
    masked). Used identically by the index maps (to clamp — skipping the DMA)
    and the kernel (to skip compute on the repeated block)."""
    len_lim = (jnp.maximum(through, 1) - 1) // blk_k
    if not causal:
        return len_lim
    causal_lim = (start + qi * blk_q + blk_q - 1) // blk_k
    return jnp.minimum(len_lim, causal_lim)


def flash_prefill(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  start_pos: Optional[jnp.ndarray] = None,
                  kv_valid_through: Optional[jnp.ndarray] = None,
                  causal: bool = True,
                  block_q: int = 256, block_k: int = 256,
                  interpret: Optional[bool] = None) -> jnp.ndarray:
    """Blockwise flash attention for (chunked) prefill.

    q: (B, S, H, HD) — the current chunk, at absolute positions
    ``start_pos[b] + i``; k, v: (B, T, KV, HD) — the full cache buffer;
    kv_valid_through: (B,) number of live cache rows (= start_pos + seq_lens).
    Matches ``ops.attention.mha_prefill`` with positional args derived the way
    ``models.llama.prefill`` derives them.
    """
    B, S, H, HD = q.shape
    T, KV = k.shape[1], k.shape[2]
    G = H // KV
    blk_q = _pick_block(S, block_q)
    blk_k = _pick_block(T, block_k)
    if start_pos is None:
        start_pos = jnp.zeros((B,), jnp.int32)
    if kv_valid_through is None:
        kv_valid_through = jnp.full((B,), T, jnp.int32)
    if interpret is None:
        interpret = _interpret_default()

    qt = q.transpose(0, 2, 1, 3)               # (B, H, S, HD)
    kt = k.transpose(0, 2, 1, 3)               # (B, KV, T, HD)
    vt = v.transpose(0, 2, 1, 3)

    def q_map(b, h, qi, ki, starts, throughs):
        return (b, h, qi, 0)

    def kv_map(b, h, qi, ki, starts, throughs):
        lim = _kv_block_limit(starts[b], throughs[b], qi, blk_q, blk_k, causal)
        return (b, h // G, jnp.minimum(ki, lim), 0)

    kernel = functools.partial(_flash_kernel, blk_q=blk_q, blk_k=blk_k,
                               scale=1.0 / (HD ** 0.5), causal=causal)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, S // blk_q, T // blk_k),
            in_specs=[
                pl.BlockSpec((1, 1, blk_q, HD), q_map),
                pl.BlockSpec((1, 1, blk_k, HD), kv_map),
                pl.BlockSpec((1, 1, blk_k, HD), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, blk_q, HD), q_map),
            scratch_shapes=[
                pltpu.VMEM((blk_q, HD), jnp.float32),
                pltpu.VMEM((blk_q, 128), jnp.float32),
                pltpu.VMEM((blk_q, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, HD), q.dtype),
        interpret=interpret,
    )(start_pos.astype(jnp.int32), kv_valid_through.astype(jnp.int32),
      qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Ragged decode
# ---------------------------------------------------------------------------

def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, blk_t: int, scale: float):
    # grid (B, KV, nT) — per (slot, kv head); all dots are plain 2D matmuls
    # (Mosaic does not lower batched dots with mismatched batch positions).
    b = pl.program_id(0)
    ti = pl.program_id(2)
    nt = pl.num_programs(2)

    @pl.when(ti == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = lens_ref[b]
    lim = (jnp.maximum(length, 1) - 1) // blk_t

    @pl.when(ti <= lim)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)        # (G, HD)
        k = k_ref[0].astype(jnp.float32)           # (blk_t, HD)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        t_pos = ti * blk_t + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(t_pos < length, s, NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ti == nt - 1)
    def _emit():
        o_ref[0, 0] = (acc_ref[...] /
                       jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


def _ragged_paged_kernel(lens_ref, pos0_ref, qnum_ref, table_ref, layer_ref,
                         q_ref, k_ref, v_ref, *rest, ps: int, scale: float,
                         KV: int, G: int, HD: int, quant: bool, Qb: int = 1):
    # rest = (ks_ref, vs_ref, o_ref, acc, m, l) when quant else (o_ref, …):
    # a quantized pool carries int8 pages + (KV, ps) per-token-per-head
    # scale tiles; the dequant folds past the dots (scores/probabilities
    # row-scaled), so HBM only ever sees int8 KV bytes
    if quant:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, acc_ref, m_ref, l_ref = rest
    # Grid (R, maxp): ONE grid step per (ragged row, logical page), all KV
    # heads processed in a static in-kernel loop — at serving shapes the
    # per-page work is tiny, so a (R, KV, pages) grid is overhead-bound
    # (profiled at ~0.25 us/step x 1024 steps x 28 layers ≈ 7 ms per decode
    # step on a 3B model; this layout cuts the grid by KV x). ti is the
    # LOGICAL page index (position ti*ps + row); table_ref/layer_ref ride
    # in SMEM for the index maps alone.
    #
    # Each grid row r is an INDEPENDENT ragged span of up to Qb queries
    # against its own page-table row — the mixed-phase formulation
    # (ROADMAP item 2, arxiv 2604.15464): a decode slot is a row with
    # q_num=1, a speculative-verify slot a row with q_num=W drafted
    # positions, a prefill chunk a run of rows covering its whole chunk —
    # one dispatch serves any mix. Per-row SMEM metadata:
    #   lens_ref[r]  — live KV rows (INCLUDING this row's queries' writes);
    #   pos0_ref[r]  — absolute position of the row's query 0 (query j sits
    #                  at pos0+j and attends keys at positions <= pos0+j);
    #   qnum_ref[r]  — valid queries; rows with 0 are SKIPPED (their page
    #                  DMAs clamp to a repeated block and compute never
    #                  runs), not padded — an idle row costs ~nothing.
    del table_ref, layer_ref
    b = pl.program_id(0)
    ti = pl.program_id(1)
    nt = pl.num_programs(1)

    @pl.when(ti == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    length = lens_ref[b]
    pos0 = pos0_ref[b]
    q_num = qnum_ref[b]
    lim = (jnp.maximum(length, 1) - 1) // ps
    QG = Qb * G

    @pl.when((ti <= lim) & (q_num > 0))
    def _compute():
        q = q_ref[0].astype(jnp.float32)           # (KV*Qb*G, HD)
        k = k_ref[0].astype(jnp.float32)           # (ps, KV*HD)
        v = v_ref[0].astype(jnp.float32)
        # per-query causal limit: row r of a kv block is query r // G, at
        # absolute position pos0 + r // G; padding queries (>= q_num) are
        # fully masked — their output rows are the caller's to discard
        t_pos = ti * ps + jax.lax.broadcasted_iota(jnp.int32, (QG, ps), 1)
        q_ix = jax.lax.broadcasted_iota(jnp.int32, (QG, ps), 0) // G
        t_mask = (t_pos <= pos0 + q_ix) & (q_ix < q_num)
        for kv in range(KV):                       # static unroll over heads
            k_head = k[:, kv * HD:(kv + 1) * HD]
            v_head = v[:, kv * HD:(kv + 1) * HD]
            s = jax.lax.dot_general(
                q[kv * QG:(kv + 1) * QG], k_head,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # (QG, ps)
            if quant:
                # dequant folded past the dot: q·(k_t·s_t) = (q·k_t)·s_t —
                # one (1, ps) row-scale of the score matrix instead of a
                # per-element K dequant; scales are stored (KV, ps), a
                # native f32 tile
                s = s * ks_ref[0][kv:kv + 1, :]
            s = jnp.where(t_mask, s, NEG_INF)
            rows = slice(kv * QG, (kv + 1) * QG)
            m_prev = m_ref[rows, :1]
            l_prev = l_ref[rows, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_ref[rows, :] = jnp.broadcast_to(
                alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True),
                (QG, l_ref.shape[1]))
            m_ref[rows, :] = jnp.broadcast_to(m_new, (QG, m_ref.shape[1]))
            if quant:
                # Σ_t p_t·(v_t·s_t) = (p·s) @ v — row-scale p instead of
                # dequantizing V
                p = p * vs_ref[0][kv:kv + 1, :]
            acc_ref[rows, :] = acc_ref[rows, :] * alpha + jax.lax.dot_general(
                p, v_head, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(ti == nt - 1)
    def _emit():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[:, :1], 1e-30)).astype(o_ref.dtype)


def ragged_paged_attention(q: jnp.ndarray, k_pages: jnp.ndarray,
                           v_pages: jnp.ndarray, row_tables: jnp.ndarray,
                           kv_lens: jnp.ndarray, q_pos0: jnp.ndarray,
                           q_num: jnp.ndarray,
                           layer: Optional[jnp.ndarray] = None,
                           pages_per_layer: Optional[int] = None,
                           k_scales: Optional[jnp.ndarray] = None,
                           v_scales: Optional[jnp.ndarray] = None,
                           interpret: Optional[bool] = None) -> jnp.ndarray:
    """Mixed-phase ragged attention straight off the paged KV pool.

    The engine's single attention dispatch for any mix of serving phases
    (ROADMAP item 2 / arxiv 2604.15464): q is (R, Qb, H, HD) — R
    independent ragged rows of up to Qb queries each, every row reading its
    OWN page-table row of the shared pool. A decode slot contributes one
    row with ``q_num=1``, a speculative-verify slot one row with its draft
    width, a prefill chunk ``C / Qb`` consecutive rows covering the whole
    chunk; empty rows (``q_num=0``) are skipped outright — their page DMAs
    clamp to a repeated block and compute never runs.

    Per-row metadata (scalar-prefetched SMEM):
      row_tables: (R, maxp) logical→physical page ids;
      kv_lens:    (R,) live KV rows, INCLUDING the row's own queries' writes;
      q_pos0:     (R,) absolute position of query 0 — query j sits at
                  ``q_pos0 + j`` and attends keys at positions <= that
                  (per-row causal offsets);
      q_num:      (R,) valid queries; output rows past q_num are garbage
                  (finite, never NaN) the caller discards.

    k_pages, v_pages: the physical pool in the kernel's NATIVE flat layout
    (N, page, KV*HD) — for a multi-layer pool, N = L*P with ``layer`` a
    ()/(1,) dynamic layer index and ``pages_per_layer`` = P, so the
    caller's layer loop neither slices nor reshapes the pool (on a multi-GB
    loop-carried buffer either would force XLA to materialize a full copy
    per layer). Each grid step DMAs exactly one physical page chosen by
    scalar-prefetched table lookup — no dense gather of the pool ever
    materializes — and pages past a row's kv_len clamp to a repeated index
    so their DMA is skipped entirely.

    ``k_scales``/``v_scales`` (N, KV, page) switch the kernel to its int8
    variant: pages hold int8 with the dequant folded past the dots —
    scores and probabilities are row-scaled by the per-token scales
    ((KV, page) blocks are native f32 tiles), so no per-element dequant
    runs in the kernel (the TRT-LLM kv-cache-quantization capability).
    """
    R, Qb, H, HD = q.shape
    N, ps, KVHD = k_pages.shape
    KV = KVHD // HD
    P = pages_per_layer if pages_per_layer is not None else N
    if layer is None:
        layer = jnp.zeros((), jnp.int32)
    maxp = row_tables.shape[1]
    G = H // KV
    quant = k_scales is not None
    if interpret is None:
        interpret = _interpret_default()

    # kv-major rows so the kernel's per-head slicing holds for any Qb:
    # row = kv*(Qb*G) + qi*G + g
    qg = (q.reshape(R, Qb, KV, G, HD).transpose(0, 2, 1, 3, 4)
          .reshape(R, KV * Qb * G, HD))

    def q_map(r, ti, lens, pos0, qnum, table, lyr):
        return (r, 0, 0)

    def kv_map(r, ti, lens, pos0, qnum, table, lyr):
        lim = (jnp.maximum(lens[r], 1) - 1) // ps
        return (lyr[0] * P + table[r, jnp.minimum(ti, lim)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, KV * Qb * G, HD), q_map),
        pl.BlockSpec((1, ps, KV * HD), kv_map),
        pl.BlockSpec((1, ps, KV * HD), kv_map),
    ]
    args = [qg, k_pages, v_pages]
    if quant:
        in_specs += [pl.BlockSpec((1, KV, ps), kv_map),
                     pl.BlockSpec((1, KV, ps), kv_map)]
        args += [k_scales, v_scales]

    kernel = functools.partial(_ragged_paged_kernel, ps=ps,
                               scale=1.0 / (HD ** 0.5), KV=KV, G=G, HD=HD,
                               quant=quant, Qb=Qb)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=(R, maxp),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, KV * Qb * G, HD), q_map),
            scratch_shapes=[
                pltpu.VMEM((KV * Qb * G, HD), jnp.float32),
                pltpu.VMEM((KV * Qb * G, 128), jnp.float32),
                pltpu.VMEM((KV * Qb * G, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((R, KV * Qb * G, HD), q.dtype),
        interpret=interpret,
    )(kv_lens.astype(jnp.int32), q_pos0.astype(jnp.int32),
      q_num.astype(jnp.int32), row_tables.astype(jnp.int32),
      jnp.reshape(layer, (1,)).astype(jnp.int32), *args)
    return (out.reshape(R, KV, Qb, G, HD).transpose(0, 2, 1, 3, 4)
            .reshape(R, Qb, H, HD))


def paged_decode(q: jnp.ndarray, k_pages: jnp.ndarray, v_pages: jnp.ndarray,
                 page_table: jnp.ndarray, lengths: jnp.ndarray,
                 layer: Optional[jnp.ndarray] = None,
                 pages_per_layer: Optional[int] = None,
                 k_scales: Optional[jnp.ndarray] = None,
                 v_scales: Optional[jnp.ndarray] = None,
                 interpret: Optional[bool] = None) -> jnp.ndarray:
    """Decode attention straight off the paged KV pool, 1..Q queries/slot.

    The uniform-row special case of :func:`ragged_paged_attention`: every
    slot is one ragged row of exactly Q valid queries ending at its length.
    q: (B, Q, H, HD) — Q consecutive positions per slot, query qi at
    position ``lengths[b] - Q + qi`` (Q=1 is classic decode; Q>1 is the
    speculative-verify step: drafted tokens' KV is already written and the
    per-query causal offset masks each query to its own prefix).
    ``lengths`` counts live rows INCLUDING all Q queries' writes.
    Matches ``mha_decode`` on the gathered-dense view; see
    :func:`ragged_paged_attention` for the pool layout and int8 contract.
    """
    B, Q, _, _ = q.shape
    lengths = lengths.astype(jnp.int32)
    return ragged_paged_attention(
        q, k_pages, v_pages, page_table, lengths, lengths - Q,
        jnp.full((B,), Q, jnp.int32), layer=layer,
        pages_per_layer=pages_per_layer, k_scales=k_scales,
        v_scales=v_scales, interpret=interpret)


def ragged_decode(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
                  lengths: jnp.ndarray, block_t: int = 512,
                  interpret: Optional[bool] = None) -> jnp.ndarray:
    """Single-token decode attention over a ragged slot batch.

    q: (B, 1, H, HD); k_cache, v_cache: (B, T, KV, HD); lengths: (B,) live
    rows per slot (including the token written this step). Matches
    ``ops.attention.mha_decode``.
    """
    B, _, H, HD = q.shape
    T, KV = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    blk_t = _pick_block(T, block_t)
    if interpret is None:
        interpret = _interpret_default()

    qg = q.reshape(B, KV, G, HD)
    # (B, T, KV, HD) → (B, T, KV*HD) is a free view of the contiguous cache;
    # each program DMAs its head's 128-wide column band of blk_t rows.
    kf = k_cache.reshape(B, T, KV * HD)
    vf = v_cache.reshape(B, T, KV * HD)

    def q_map(b, kv, ti, lens):
        return (b, kv, 0, 0)

    def kv_map(b, kv, ti, lens):
        lim = (jnp.maximum(lens[b], 1) - 1) // blk_t
        return (b, jnp.minimum(ti, lim), kv)

    kernel = functools.partial(_decode_kernel, blk_t=blk_t,
                               scale=1.0 / (HD ** 0.5))
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, KV, T // blk_t),
            in_specs=[
                pl.BlockSpec((1, 1, G, HD), q_map),
                pl.BlockSpec((1, blk_t, HD), kv_map),
                pl.BlockSpec((1, blk_t, HD), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, G, HD), q_map),
            scratch_shapes=[
                pltpu.VMEM((G, HD), jnp.float32),
                pltpu.VMEM((G, 128), jnp.float32),
                pltpu.VMEM((G, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, KV, G, HD), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), qg, kf, vf)
    return out.reshape(B, 1, H, HD)
