"""Pallas TPU kernels for the serving hot path.

The XLA einsum paths in ``ops.attention`` are the numerical reference; every
kernel here is validated against them (tests/test_pallas.py, interpret mode on
CPU + compiled on TPU).
"""

from generativeaiexamples_tpu.ops.pallas.attention import (  # noqa: F401
    flash_prefill,
    paged_decode,
    paged_decode_supported,
    ragged_decode,
    ragged_paged_attention,
    ragged_paged_supported,
    decode_supported,
    prefill_supported,
)
