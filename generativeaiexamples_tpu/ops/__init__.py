"""TPU compute primitives: norms, RoPE, attention (XLA + Pallas), sampling.

These are the in-tree replacements for the fused kernels that live inside the
reference's external NIM/TRT-LLM containers (SURVEY §2.5). XLA fuses the
elementwise chains into the matmuls; Pallas kernels cover what fusion can't
(flash prefill attention, ragged paged decode attention).
"""

from generativeaiexamples_tpu.ops.layers import rms_norm, swiglu, rotary_embedding, apply_rope  # noqa: F401
from generativeaiexamples_tpu.ops.attention import mha_prefill, mha_decode  # noqa: F401
from generativeaiexamples_tpu.ops.sampling import sample_logits, SamplingParams  # noqa: F401
