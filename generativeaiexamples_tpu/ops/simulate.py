"""Discrete-event trace replay + what-if simulator (docs/simulation.md).

Time-travel observability: load a canonical fleet event trace
(observability/trace.py), reconstruct its arrival process, and re-drive
the REAL policy objects — ``engine/scheduler.Scheduler``, the QoS
admission plane (engine/qos.py), the KV spill pool / prefix tier
(engine/kv_tier.py), and the router's placement primitives
(server/failover.py) — on a :class:`core.clock.VirtualClock` across N
simulated replicas. No mocks of policy code: what admitted, preempted,
spilled, promoted, or shed in the simulation is decided by exactly the
code that would decide it live. Only the DEVICE is faked
(engine/fakecore.py), and it charges perfmodel-estimated seconds per
dispatch, which is what advances the virtual clock.

Determinism: every replica's dispatch executor is replaced with an
inline (same-thread) one, so futures resolve synchronously and a run is
a pure function of (workload, knobs). Prompts are synthesized from
``(request_id, prompt_tokens)``, so a trace RECORDED by this simulator
replays token-identically — ``make simulate-smoke`` asserts zero drift.
Traces recorded from live traffic replay the same arrival process and
cost model but synthetic token content; the fidelity report
(:func:`fidelity_report`) quantifies the per-metric drift instead of
assuming it away (caveats in docs/simulation.md).

What-if knobs (the CLI): replica count, tenant weights/quotas
(``APP_QOS_*``), spill/tier bytes (``APP_KV_SPILL_MB`` /
``APP_KV_TIER``), and ``tuned_prefill_share`` (``APP_PREFILL_SHARE``,
parallel/topology.py). A 100-replica synthetic run completes in seconds
on CPU (``make simulate``) because virtual seconds cost nothing — only
dispatch bookkeeping does.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability.trace import TRACE, read_jsonl

_QUANTUM_S = 2e-4          # virtual step when no dispatch consumed time
_DEADLINE_MS_DEFAULT = 8000.0


def _jain_index(values: Sequence[float]) -> Optional[float]:
    """Jain's fairness index: (Σx)²/(n·Σx²) — 1.0 = equal shares (the
    same expression bench.py's goodput round reports)."""
    values = [float(v) for v in values]
    if not values:
        return None
    sq = sum(v * v for v in values)
    if sq <= 0:
        return None
    return round(sum(values) ** 2 / (len(values) * sq), 4)


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    ix = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return round(sorted_vals[ix], 6)


class _InlineExecutor:
    """Same-thread stand-in for the scheduler's fetcher pool: futures
    resolve before submit() returns, so replay never races a thread
    scheduler — the determinism the round-trip fidelity test asserts."""

    def submit(self, fn, *args, **kw) -> Future:
        fut: Future = Future()
        try:
            fut.set_result(fn(*args, **kw))
        except BaseException as exc:   # tpulint: disable=except-swallow -- mirrors Executor.submit semantics: the error is DELIVERED via the future; the scheduler's fetch path re-raises it
            fut.set_exception(exc)
        return fut

    def shutdown(self, wait: bool = True, **kw) -> None:
        return None


# ---------------------------------------------------------------- workload


@dataclass
class Arrival:
    """One reconstructed (or synthesized) request arrival."""

    t: float                  # virtual arrival instant (mono seconds)
    rid: str
    tenant: str
    prompt_tokens: int
    max_tokens: int
    slo_class: str = ""
    deadline_s: Optional[float] = None
    affinity: str = ""        # router stickiness key (conversation id)
    prompt: List[int] = field(default_factory=list)


def _family_of(rid: str, families: int = 64) -> int:
    h = hashlib.blake2b(rid.encode("utf-8"), digest_size=4).digest()
    return int.from_bytes(h, "little") % max(1, families)


def synth_prompt(rid: str, n: int) -> List[int]:
    """Deterministic prompt content from (request_id, length): record and
    replay runs regenerate the SAME bytes, so FakeCore's content-hash
    sampler produces token-identical streams — the round-trip fidelity
    contract. Same-family openings repeat across rids (prefix-cache and
    tier promotion stay exercised)."""
    fam = _family_of(rid)
    return [32 + (i * 11 + fam * 7) % 150 for i in range(max(1, n))]


def synthetic_arrivals(requests: int = 50, seed: int = 0,
                       deadline_ms: float = _DEADLINE_MS_DEFAULT,
                       antagonist: bool = True,
                       max_tokens: int = 16,
                       prompt_tokens: int = 24,
                       pace_s: float = 0.05) -> List[Arrival]:
    """The GOODPUT-round workload shape (bench.py run_goodput_round):
    one ``flood`` tenant fires everything at t=0 (best_effort,
    sheddable) while ``obey_a``/``obey_b`` pace their interactive-class
    requests — the antagonist scenario the QoS what-if sweep runs over.
    ``antagonist=False`` degrades to a single paced tenant."""
    out: List[Arrival] = []
    if not antagonist:
        for i in range(requests):
            rid = f"sim-{seed:03d}-{i:05d}"
            out.append(Arrival(
                t=i * pace_s, rid=rid, tenant="solo",
                prompt_tokens=prompt_tokens, max_tokens=max_tokens,
                slo_class="interactive",
                deadline_s=deadline_ms / 1000.0,
                affinity=f"conv-{_family_of(rid, 8)}",
                prompt=synth_prompt(rid, prompt_tokens)))
        return out
    obey_n = max(1, requests // 3)
    flood_n = requests - 2 * obey_n
    for tenant_ix, tenant in enumerate(("obey_a", "obey_b")):
        for i in range(obey_n):
            rid = f"sim-{seed:03d}-{tenant}-{i:05d}"
            out.append(Arrival(
                t=i * pace_s + tenant_ix * pace_s / 2, rid=rid,
                tenant=tenant, prompt_tokens=prompt_tokens,
                max_tokens=max_tokens, slo_class="interactive",
                deadline_s=deadline_ms / 1000.0,
                affinity=f"{tenant}-conv-{_family_of(rid, 4)}",
                prompt=synth_prompt(rid, prompt_tokens)))
    for i in range(max(0, flood_n)):
        rid = f"sim-{seed:03d}-flood-{i:05d}"
        out.append(Arrival(
            t=0.0, rid=rid, tenant="flood",
            prompt_tokens=prompt_tokens, max_tokens=max_tokens,
            slo_class="best_effort", deadline_s=deadline_ms / 1000.0,
            affinity=f"flood-conv-{_family_of(rid, 4)}",
            prompt=synth_prompt(rid, prompt_tokens)))
    return out


def arrivals_from_trace(records: List[dict]) -> List[Arrival]:
    """Reconstruct the arrival process from a trace's ``submit`` records:
    virtual arrival offsets are the recorded mono stamps rebased to the
    first submission. Prompt CONTENT is synthesized from (rid,
    prompt_tokens) — exact for simulator-recorded traces, a documented
    approximation for live ones."""
    subs = [r for r in records if r.get("kind") == "submit"
            and not r.get("handoff")]
    if not subs:
        raise ValueError("trace holds no submit records — nothing to "
                         "replay (was APP_TRACE=on during recording?)")
    t0 = min(float(r.get("mono", 0.0)) for r in subs)
    # simulator-recorded traces carry an "arrival" supplement with the
    # router affinity key (client-side state no scheduler record has);
    # live traces fall back to the learned prefix hash, then the rid
    affinity = {str(r.get("rid")): str(r.get("affinity", "") or "")
                for r in records if r.get("kind") == "arrival"}
    out: List[Arrival] = []
    for r in sorted(subs, key=lambda r: (float(r.get("mono", 0.0)),
                                         int(r.get("seq", 0)))):
        rid = str(r.get("rid", "")) or f"trace-{r.get('seq', 0)}"
        n = int(r.get("prompt_tokens", 1) or 1)
        out.append(Arrival(
            t=float(r.get("mono", 0.0)) - t0, rid=rid,
            tenant=str(r.get("tenant", "") or ""),
            prompt_tokens=n,
            max_tokens=int(r.get("max_tokens", 16) or 16),
            slo_class=str(r.get("slo", "") or ""),
            deadline_s=r.get("deadline_s"),
            affinity=(affinity.get(rid)
                      or str(r.get("prefix", "") or rid)),
            prompt=synth_prompt(rid, n)))
    return out


# ---------------------------------------------------------------- replicas


@dataclass
class SimConfig:
    """What-if knobs — each maps to the env contract the live stack
    already honors, applied only for the replica-construction scope."""

    replicas: int = 1
    qos: str = "off"                       # APP_QOS
    tenant_weights: str = ""               # APP_QOS_TENANT_WEIGHTS
    tenant_quota: str = ""                 # APP_QOS_TOKENS_PER_S
    tier_mb: int = 0                       # APP_KV_SPILL_MB (+ tier mode)
    tier_mode: str = ""                    # "" | "prefix"
    prefill_share: Optional[float] = None  # APP_PREFILL_SHARE
    batch: int = 4
    max_seq: int = 96
    page_size: int = 8
    num_pages: int = 0
    chunk: int = 16
    steps: int = 2
    group: int = 4
    prefix_cache: bool = True

    def env(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        if self.qos and self.qos != "off":
            out["APP_QOS"] = self.qos
            if self.tenant_weights:
                out["APP_QOS_TENANT_WEIGHTS"] = self.tenant_weights
            if self.tenant_quota:
                out["APP_QOS_TOKENS_PER_S"] = self.tenant_quota
        if self.tier_mb > 0:
            out["APP_KV_SPILL_MB"] = str(self.tier_mb)
            if self.tier_mode:
                out["APP_KV_TIER"] = self.tier_mode
        if self.prefill_share is not None:
            out["APP_PREFILL_SHARE"] = str(self.prefill_share)
        return out


class SimReplica:
    """One simulated engine worker: FakeCore (perfmodel-costed virtual
    device) + the REAL Scheduler, its dispatch executor made inline."""

    def __init__(self, ix: int, cfg: SimConfig) -> None:
        from generativeaiexamples_tpu.engine.fakecore import FakeCore
        from generativeaiexamples_tpu.engine.scheduler import Scheduler
        from generativeaiexamples_tpu.engine.tokenizer import ByteTokenizer
        self.ix = ix
        self.url = f"sim://replica/{ix}"
        self.core = FakeCore(
            batch=cfg.batch, max_seq=cfg.max_seq, page_size=cfg.page_size,
            num_pages=cfg.num_pages, chunk=cfg.chunk, steps=cfg.steps,
            group=cfg.group, prefix_cache=cfg.prefix_cache)
        self.sched = Scheduler(self.core, ByteTokenizer())
        # never .start(): the simulator's loop IS the driver thread
        self.sched._fetcher.shutdown(wait=False)
        self.sched._fetcher = _InlineExecutor()

    def close(self) -> None:
        self.sched._fetcher.shutdown(wait=False)


def build_replicas(cfg: SimConfig) -> List[SimReplica]:
    """Construct N replicas under the config's env scope (the same
    env-var contract the live worker boot reads), restoring the caller's
    environment afterwards."""
    env = cfg.env()
    saved = {k: os.environ.get(k) for k in
             ("APP_QOS", "APP_QOS_TENANT_WEIGHTS", "APP_QOS_TOKENS_PER_S",
              "APP_KV_SPILL_MB", "APP_KV_TIER", "APP_PREFILL_SHARE")}
    os.environ.update(env)
    for k in saved:
        if k not in env:
            os.environ.pop(k, None)
    try:
        return [SimReplica(i, cfg) for i in range(max(1, cfg.replicas))]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ------------------------------------------------------------------ router


class SimRouter:
    """Placement over simulated replicas driving the REAL router policy
    primitives (server/failover.py): real ``_Worker`` scoring cards fed
    from each replica's real ``load_stats()``, the real rendezvous hash
    for prefix affinity, and the same slack/promote comparison ``_pick``
    runs — without the HTTP probe machinery around them."""

    def __init__(self, replicas: List[SimReplica],
                 affinity_slack: Optional[float] = None) -> None:
        from generativeaiexamples_tpu.server import failover
        self._failover = failover
        self._replicas = replicas
        self._workers = [failover._Worker(r.url) for r in replicas]
        self.affinity_slack = (
            affinity_slack if affinity_slack is not None
            else float(os.environ.get("APP_ROUTER_AFFINITY_SLACK", "")
                       or 1.0))
        self.outcomes: Dict[str, int] = {}

    def _refresh(self) -> None:
        for w, r in zip(self._workers, self._replicas):
            stats = r.sched.load_stats()
            w.running = int(stats.get("running", 0))
            w.prefilling = int(stats.get("prefilling", 0))
            w.waiting = int(stats.get("waiting", 0))
            w.batch = int(stats.get("batch", 0) or r.core.batch)
            w.prefix_hit_frac = float(stats.get("prefix_hit_frac", 0.0))
            hot = stats.get("kv_tier_hot")
            w.kv_tier_hot = (frozenset(str(h) for h in hot)
                             if hot else frozenset())

    def place(self, arrival: Arrival) -> int:
        """Replica index for this arrival — least-loaded with rendezvous
        affinity and tier-promote override, exactly the live ordering."""
        self._refresh()
        workers = self._workers
        best = min(workers, key=lambda w: w.score)
        outcome = "load"
        if arrival.affinity and len(workers) > 1:
            pref = self._failover.FailoverLLM._rendezvous(
                arrival.affinity, workers)
            slack = self.affinity_slack * (1.0 + pref.prefix_hit_frac)
            h0 = ""
            if arrival.prompt:
                h0 = self._replicas[0].sched.prefix_key_hex(arrival.prompt)
            promote = None
            if h0 and h0 not in pref.kv_tier_hot:
                adv = [w for w in workers if h0 in w.kv_tier_hot]
                if adv:
                    promote = min(adv, key=lambda w: w.score)
            if promote is not None and promote.score <= best.score + slack:
                best = promote
                outcome = "promote"
            elif pref.score <= best.score + slack:
                best = pref
                outcome = "affinity"
        best.dispatched += 1
        best.total_dispatched += 1
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        return workers.index(best)


# ------------------------------------------------------------------- drive


def simulate(arrivals: List[Arrival], cfg: SimConfig,
             record_trace: Optional[str] = None) -> Dict[str, Any]:
    """Run the workload to completion on a virtual clock; returns the
    flight/goodput-family metric summary plus per-request records.
    ``record_trace`` arms APP_TRACE-equivalent recording during the run
    and dumps the ring as JSONL to that path (the simulate-smoke
    round-trip records through here)."""
    from generativeaiexamples_tpu.engine.scheduler import Request

    wall0 = time.perf_counter()
    arrivals = sorted(arrivals, key=lambda a: (a.t, a.rid))
    vc = clock.VirtualClock()
    # the run ALWAYS records through the event-trace ring (no file sink
    # unless asked): finish order and the fidelity comparison read the
    # trace's own total order (seq), not a per-tick approximation. The
    # caller's live trace state is restored afterwards.
    prev_trace = (TRACE.enabled, TRACE.path)
    TRACE.configure(mode="on", path="")
    TRACE.reset()
    # the SLO plane is process-global (like REGISTRY/FLIGHT) and its
    # burn-rate windows drive real shedding in the scheduler admission
    # pass — a simulation must neither inherit the live process's
    # pressure (a critical window from earlier traffic would shed the
    # simulated workload) nor leak its simulated breaches back out.
    # Scope a fresh tracker on the VIRTUAL clock for the run's duration.
    prev_slo = slo_mod.SLO
    slo_mod.SLO = slo_mod.SloTracker(clock=clock.mono)
    with clock.use(vc):
        replicas: List[SimReplica] = []
        reqs: List[tuple] = []
        finished: set = set()
        next_ix = 0
        ticks = 0
        tick_cap = max(20000, 400 * len(arrivals))
        try:
            replicas = build_replicas(cfg)
            router = SimRouter(replicas)
            while True:
                now = clock.mono()
                while (next_ix < len(arrivals)
                       and arrivals[next_ix].t <= now + 1e-12):
                    a = arrivals[next_ix]
                    next_ix += 1
                    req = Request(prompt_ids=list(a.prompt),
                                  max_tokens=a.max_tokens,
                                  temperature=0.0, tenant=a.tenant,
                                  request_id=a.rid, seed=1,
                                  slo_class=a.slo_class,
                                  deadline_s=a.deadline_s)
                    r_ix = router.place(a)
                    if TRACE.enabled:
                        # simulator supplement: the router affinity key is
                        # client-side state no scheduler record carries —
                        # replaying THIS trace must place with the same key
                        TRACE.emit("arrival", rid=a.rid,
                                   affinity=a.affinity, replica=r_ix)
                    replicas[r_ix].sched.submit(req)
                    reqs.append((req, a, r_ix))
                worked = False
                dt = 0.0
                for rep in replicas:
                    if rep.sched._tick():
                        worked = True
                    dt = max(dt, rep.core.take_consumed())
                for req, _a, _r in reqs:
                    if (req.finished_at is not None
                            and req.request_id not in finished):
                        finished.add(req.request_id)
                ticks += 1
                if ticks > tick_cap:
                    raise RuntimeError(
                        f"simulator livelock: {len(finished)}/"
                        f"{len(arrivals)} finished after {ticks} ticks")
                if (len(finished) >= len(arrivals)
                        and next_ix >= len(arrivals)):
                    break
                if dt > 0:
                    vc.advance(dt)
                elif not worked and next_ix < len(arrivals):
                    vc.advance_to(max(clock.mono() + _QUANTUM_S,
                                      arrivals[next_ix].t))
                else:
                    # host-only tick (admission, fetch bookkeeping, or a
                    # quota-throttled idle pass): a small quantum keeps
                    # refill/deadline clocks moving
                    vc.advance(_QUANTUM_S if not worked else 1e-5)
            span_s = clock.mono()
        finally:
            slo_mod.SLO = prev_slo
            for rep in replicas:
                rep.close()
    # the trace's seq field is the run's total order — finish order reads
    # it directly (two finishes inside one tick keep their true order)
    finish_order = [str(r.get("rid")) for r in sorted(
        (r for r in TRACE.records() if r.get("kind") == "finish"),
        key=lambda r: int(r.get("seq", 0)))]
    if record_trace is not None:
        TRACE.dump_jsonl(record_trace)
    TRACE.reset()
    TRACE.configure(mode="on" if prev_trace[0] else "off",
                    path=prev_trace[1] or "")
    result = _summarize(reqs, finish_order, span_s, cfg, router)
    result["ticks"] = ticks
    result["wall_seconds"] = round(time.perf_counter() - wall0, 3)
    return result


def _summarize(reqs: List[tuple], finish_order: List[str], span_s: float,
               cfg: SimConfig, router: SimRouter) -> Dict[str, Any]:
    per_req: List[dict] = []
    tenants: Dict[str, dict] = {}
    finishes: Dict[str, int] = {}
    for req, a, r_ix in reqs:
        fin = (req.finish_reason or ("error" if req.error else "none"))
        finishes[fin] = finishes.get(fin, 0) + 1
        ttft = (round(req.first_token_at - req.submitted_at, 6)
                if req.first_token_at is not None else None)
        e2e = (round(req.finished_at - req.submitted_at, 6)
               if req.finished_at is not None else None)
        in_deadline = (req.error is None and e2e is not None
                       and (req.deadline_s is None or e2e <= req.deadline_s))
        per_req.append({
            "rid": req.request_id, "tenant": req.tenant, "replica": r_ix,
            "prompt_tokens": len(req.prompt_ids),
            "completion_tokens": req.completion_tokens,
            "finish": fin, "ttft_s": ttft, "e2e_s": e2e,
            "goodput": bool(in_deadline),
            "preemptions": req.preemptions,
            "spill_resumes": req.spill_resumes,
            "prefix_hit_tokens": req.prefix_hit_tokens,
            "tier_hit_tokens": req.tier_hit_tokens,
        })
        t = tenants.setdefault(req.tenant or "anon", {
            "requests": 0, "completion_tokens": 0, "goodput": 0,
            "ttfts": [], "sheds": 0})
        t["requests"] += 1
        t["completion_tokens"] += req.completion_tokens
        t["goodput"] += int(in_deadline)
        if req.slo_outcome == "shed":
            t["sheds"] += 1
        if ttft is not None:
            t["ttfts"].append(ttft)
    per_tenant: Dict[str, dict] = {}
    for name, t in sorted(tenants.items()):
        ttfts = sorted(t.pop("ttfts"))
        per_tenant[name] = {
            **t,
            "goodput_frac": round(t["goodput"] / t["requests"], 4),
            "tok_s": (round(t["completion_tokens"] / span_s, 2)
                      if span_s > 0 else 0.0),
            "ttft_p50_s": _pct(ttfts, 0.50),
            "ttft_p95_s": _pct(ttfts, 0.95),
        }
    obeying = [v for k, v in per_tenant.items() if k != "flood"]
    total_completion = sum(r["completion_tokens"] for r in per_req)
    return {
        "replicas": cfg.replicas,
        "qos": cfg.qos,
        "tenant_weights": cfg.tenant_weights,
        "virtual_seconds": round(span_s, 6),
        "requests": {"total": len(per_req), "finishes": finishes},
        "completion_tokens": total_completion,
        "goodput_tok_s": (round(total_completion / span_s, 2)
                          if span_s > 0 else 0.0),
        "per_tenant": per_tenant,
        "jain_fair_obeying": _jain_index(
            [t["goodput_frac"] for t in obeying]) if obeying else None,
        "jain_fair_all": _jain_index(
            [t["goodput_frac"] for t in per_tenant.values()]),
        "route_outcomes": dict(sorted(router.outcomes.items())),
        "finish_order": finish_order,
        "requests_detail": per_req,
    }


# ---------------------------------------------------------------- fidelity


def fidelity_report(trace_records: List[dict],
                    result: Dict[str, Any]) -> Dict[str, Any]:
    """Per-metric drift between what a trace RECORDED and what the replay
    produced. Zero across the board for simulator-recorded traces at
    equal knobs (the smoke test's assertion); a quantified gap — not a
    silent one — for live traces (docs/simulation.md caveats)."""
    rec_fin = {str(r.get("rid")): r for r in trace_records
               if r.get("kind") == "finish"}
    sim_fin = {r["rid"]: r for r in result.get("requests_detail", [])}
    both = sorted(set(rec_fin) & set(sim_fin))
    tok_mismatch = [rid for rid in both
                    if int(rec_fin[rid].get("completion_tokens", -1))
                    != int(sim_fin[rid]["completion_tokens"])]

    def _mean(vals: List[float]) -> Optional[float]:
        return round(sum(vals) / len(vals), 6) if vals else None

    rec_order = [str(r.get("rid")) for r in sorted(
        (r for r in trace_records if r.get("kind") == "finish"),
        key=lambda r: int(r.get("seq", 0)))]
    sim_order = [rid for rid in result.get("finish_order", [])
                 if rid in rec_fin]
    rec_tok = sum(int(r.get("completion_tokens", 0) or 0)
                  for r in rec_fin.values())
    sim_tok = sum(int(r["completion_tokens"]) for r in sim_fin.values())
    rec_ttft = _mean([float(r["ttft_s"]) for r in rec_fin.values()
                      if r.get("ttft_s") is not None])
    sim_ttft = _mean([float(r["ttft_s"]) for r in sim_fin.values()
                      if r.get("ttft_s") is not None])
    return {
        "requests_traced": len(rec_fin),
        "requests_replayed": len(sim_fin),
        "matched": len(both),
        "completion_tokens": {"traced": rec_tok, "replayed": sim_tok,
                              "drift": sim_tok - rec_tok},
        "token_mismatch_rids": tok_mismatch[:32],
        "token_mismatches": len(tok_mismatch),
        "finish_order_identical": rec_order == sim_order,
        "ttft_mean_s": {"traced": rec_ttft, "replayed": sim_ttft,
                        "drift": (round(sim_ttft - rec_ttft, 6)
                                  if None not in (rec_ttft, sim_ttft)
                                  else None)},
    }


# --------------------------------------------------------------------- CLI


def sweep_tenant_weight(arrivals: List[Arrival], cfg: SimConfig,
                        multipliers: Sequence[float]) -> List[dict]:
    """What-if sweep: scale the OBEYING tenants' weight 1x→Nx against a
    fixed-weight antagonist and rerun — the acceptance check is the
    obeying tenants' goodput share moving monotonically with their
    weight."""
    out: List[dict] = []
    for m in multipliers:
        w = max(1, int(round(2 * m)))
        swept = SimConfig(**{**cfg.__dict__,
                             "qos": "fair",
                             "tenant_weights":
                                 f"obey_a={w},obey_b={w},flood=1"})
        res = simulate(list(arrivals), swept)
        obey = [t for name, t in res["per_tenant"].items()
                if name != "flood"]
        flood = res["per_tenant"].get("flood", {})
        obey_tok = sum(t["completion_tokens"] for t in obey)
        total = obey_tok + flood.get("completion_tokens", 0)
        out.append({
            "multiplier": m,
            "tenant_weights": swept.tenant_weights,
            "obeying_goodput_frac": (
                round(sum(t["goodput_frac"] for t in obey) / len(obey), 4)
                if obey else None),
            "obeying_token_share": (round(obey_tok / total, 4)
                                    if total else None),
            "obeying_ttft_p50_s": _pct(sorted(
                t["ttft_p50_s"] for t in obey
                if t["ttft_p50_s"] is not None), 0.5),
            "jain_fair_obeying": res["jain_fair_obeying"],
        })
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m generativeaiexamples_tpu.ops.simulate",
        description="Replay a fleet event trace (or a synthetic workload) "
                    "through the real scheduler/QoS/KV-tier/router policies "
                    "on a virtual clock (docs/simulation.md)")
    p.add_argument("--trace", default="", help="trace JSONL to replay "
                   "(APP_TRACE_PATH sink, /debug/trace dump, or a "
                   "simulator recording)")
    p.add_argument("--synthetic", action="store_true",
                   help="generate the goodput-round antagonist workload "
                        "instead of loading a trace")
    p.add_argument("--exemplar", default="", metavar="RID",
                   help="replay ONE captured forensics exemplar "
                        "(observability/forensics.py): filters --trace "
                        "PATH to this request's slice — or pulls it from "
                        "the in-process exemplar ring when no --trace is "
                        "given — so a captured p99 request can be "
                        "counterfactually replayed against what-if knobs")
    p.add_argument("--requests", type=int, default=60)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--qos", default="off", choices=("off", "fair"))
    p.add_argument("--tenant-weights", default="",
                   help="APP_QOS_TENANT_WEIGHTS for the run, e.g. "
                        "'obey_a=2,obey_b=2,flood=1'")
    p.add_argument("--tenant-quota", default="",
                   help="APP_QOS_TOKENS_PER_S, e.g. 'flood=150'")
    p.add_argument("--tier-mb", type=int, default=0,
                   help="host KV budget MB (APP_KV_SPILL_MB); with "
                        "--tier-mode prefix arms the prefix tier")
    p.add_argument("--tier-mode", default="", choices=("", "prefix"))
    p.add_argument("--prefill-share", type=float, default=None,
                   help="APP_PREFILL_SHARE what-if (parallel/topology.py "
                        "tuned_prefill_share)")
    p.add_argument("--deadline-ms", type=float,
                   default=_DEADLINE_MS_DEFAULT)
    p.add_argument("--pace-s", type=float, default=0.05,
                   help="synthetic obeying-tenant inter-arrival seconds; "
                        "tighten with --deadline-ms to saturate the "
                        "deadline window (sweeps are flat otherwise)")
    p.add_argument("--record-out", default="",
                   help="dump the run's own event trace JSONL here")
    p.add_argument("--sweep-weights", default="",
                   help="comma list of obeying-tenant weight multipliers "
                        "to sweep, e.g. '1,2,4'")
    p.add_argument("--out", default="", help="write the JSON report here "
                   "(default stdout)")
    args = p.parse_args(argv)

    cfg = SimConfig(replicas=args.replicas, qos=args.qos,
                    tenant_weights=args.tenant_weights,
                    tenant_quota=args.tenant_quota,
                    tier_mb=args.tier_mb, tier_mode=args.tier_mode,
                    prefill_share=args.prefill_share)
    trace_records: Optional[List[dict]] = None
    if args.exemplar:
        from generativeaiexamples_tpu.observability import (
            forensics as forensics_mod)
        if args.trace:
            slice_recs = forensics_mod.trace_slice(
                args.exemplar, read_jsonl(args.trace))
        else:
            ex = forensics_mod.FORENSICS.get(args.exemplar)
            slice_recs = list((ex or {}).get("trace") or [])
        if not slice_recs:
            p.error(f"no trace slice for exemplar {args.exemplar!r} — "
                    "pass --trace PATH (a round's JSONL sink) or run "
                    "in-process with APP_FORENSICS=on")
            return 2
        trace_records = slice_recs
        arrivals = arrivals_from_trace(trace_records)
    elif args.trace:
        trace_records = read_jsonl(args.trace)
        arrivals = arrivals_from_trace(trace_records)
    elif args.synthetic:
        arrivals = synthetic_arrivals(requests=args.requests,
                                      seed=args.seed,
                                      deadline_ms=args.deadline_ms,
                                      pace_s=args.pace_s)
    else:
        p.error("one of --trace PATH or --synthetic is required")
        return 2

    report: Dict[str, Any]
    if args.sweep_weights:
        mults = [float(x) for x in args.sweep_weights.split(",") if x]
        report = {"sweep": sweep_tenant_weight(arrivals, cfg, mults),
                  "replicas": cfg.replicas,
                  "requests": len(arrivals)}
    else:
        report = simulate(arrivals, cfg,
                          record_trace=args.record_out or None)
        if trace_records is not None:
            report["fidelity"] = fidelity_report(trace_records, report)
        # the detail list is for programmatic consumers; the CLI report
        # stays skimmable
        report.pop("requests_detail", None)
        if len(report.get("finish_order", ())) > 24:
            report["finish_order"] = report["finish_order"][:24] + ["..."]
    body = json.dumps(report, indent=2, default=str)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body + "\n")
        print(f"wrote {args.out}")
    else:
        print(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
