"""Attention: XLA reference paths for prefill and cached decode (GQA-aware).

These einsum formulations are the numerically-authoritative implementations;
the Pallas flash/ragged kernels in ``ops.pallas`` are validated against them.
Softmax is computed in float32; inputs/outputs stay in the carrier dtype
(bf16 on TPU so the matmuls hit the MXU).

GQA grouping is expressed by reshaping Q to (B, S, kv_heads, group, head_dim)
and batching the einsum over kv_heads — no materialized repeat_kv, which
would burn HBM bandwidth on (group×) duplicated K/V.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -1e30


def mha_prefill(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                q_positions: Optional[jnp.ndarray] = None,
                kv_positions: Optional[jnp.ndarray] = None,
                kv_mask: Optional[jnp.ndarray] = None,
                causal: bool = True, window: int = 0) -> jnp.ndarray:
    """Full-sequence attention.

    q: (B, S, n_heads, hd); k,v: (B, T, n_kv, hd) with n_heads % n_kv == 0.
    q_positions/kv_positions: (B, S)/(B, T) absolute positions for causal
    masking when q is a suffix of the kv sequence (chunked prefill).
    kv_mask: (B, T) validity mask for right-padded kv.
    window: sliding-window size (0 = full attention): a query at position p
    attends to kv positions in (p - window, p] (StarCoder2-family).
    Returns (B, S, n_heads, hd).
    """
    B, S, H, D = q.shape
    T, KV = k.shape[1], k.shape[2]
    G = H // KV
    qg = q.reshape(B, S, KV, G, D)
    scale = 1.0 / (D ** 0.5)
    # scores: (B, KV, G, S, T)
    scores = jnp.einsum("bskgd,btkd->bkgst", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    mask = jnp.ones((B, 1, 1, S, T), dtype=bool)
    if causal or window:
        qp = q_positions if q_positions is not None else jnp.broadcast_to(
            jnp.arange(S)[None, :], (B, S))
        kp = kv_positions if kv_positions is not None else jnp.broadcast_to(
            jnp.arange(T)[None, :], (B, T))
        if causal:
            mask = mask & (kp[:, None, None, None, :]
                           <= qp[:, None, None, :, None])
        if window:
            mask = mask & (kp[:, None, None, None, :]
                           > qp[:, None, None, :, None] - window)
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, None, None, :]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bkgst,btkd->bskgd", probs, v.astype(jnp.float32))
    return out.reshape(B, S, H, D).astype(q.dtype)


def mha_decode(q: jnp.ndarray, k_cache: jnp.ndarray, v_cache: jnp.ndarray,
               lengths: jnp.ndarray, window: int = 0) -> jnp.ndarray:
    """Single-token decode against a dense KV cache.

    q: (B, 1, n_heads, hd); k_cache,v_cache: (B, max_seq, n_kv, hd);
    lengths: (B,) number of valid cache entries (including the new token).
    window: sliding-window size (0 = full): only the last ``window`` cache
    entries participate. Returns (B, 1, n_heads, hd).
    """
    B, _, H, D = q.shape
    T, KV = k_cache.shape[1], k_cache.shape[2]
    G = H // KV
    qg = q.reshape(B, KV, G, D)
    scale = 1.0 / (D ** 0.5)
    scores = jnp.einsum("bkgd,btkd->bkgt", qg.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) * scale
    valid = jnp.arange(T)[None, :] < lengths[:, None]          # (B, T)
    if window:
        valid = valid & (jnp.arange(T)[None, :] >= lengths[:, None] - window)
    scores = jnp.where(valid[:, None, None, :], scores, NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bkgt,btkd->bkgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(B, 1, H, D).astype(q.dtype)
