"""Elementwise / normalization / rotary primitives.

Written to fuse: every op here is jnp-composable so XLA folds it into the
surrounding matmuls (HBM bandwidth is the TPU bottleneck — SURVEY §7 design
notes). Accumulations happen in float32 regardless of the bf16 carrier dtype.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm (Llama/Gemma family). Computes the moment in f32, returns x.dtype."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray,
               eps: float = 1e-5) -> jnp.ndarray:
    """Classic LayerNorm with affine weight+bias (StarCoder2-family blocks);
    moments in f32, returns x.dtype."""
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    normed = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)
            + bias.astype(jnp.float32)).astype(x.dtype)


def activate(x: jnp.ndarray, act: str) -> jnp.ndarray:
    """Ungated activation by name (plain MLPs: StarCoder2 c_fc→act→c_proj)."""
    if act == "silu":
        return silu(x)
    if act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown activation {act!r}")


def silu(x: jnp.ndarray) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    return (xf * (1.0 / (1.0 + jnp.exp(-xf)))).astype(x.dtype)


def swiglu(gate: jnp.ndarray, up: jnp.ndarray) -> jnp.ndarray:
    """SwiGLU activation: silu(gate) * up."""
    return silu(gate) * up


def glu(gate: jnp.ndarray, up: jnp.ndarray, act: str = "silu") -> jnp.ndarray:
    """Gated linear unit with a selectable gate activation:
    "silu" (llama SwiGLU) or "gelu_tanh" (gemma GeGLU)."""
    if act == "silu":
        return swiglu(gate, up)
    if act == "gelu_tanh":
        return jax.nn.gelu(gate, approximate=True) * up
    raise ValueError(f"unknown gated activation {act!r}")


def rotary_embedding(positions: jnp.ndarray, head_dim: int,
                     theta: float = 500000.0,
                     scaling: Optional[Tuple[float, float, float, int]] = None,
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for the given positions, HF split-half convention.

    positions: (..., S) int32 → cos,sin: (..., S, head_dim) where the second
    half duplicates the first (rotate-half layout, matching HF Llama so HF
    checkpoints load without permutation).

    ``scaling`` applies the llama3 rope-scaling rule as ``(factor,
    low_freq_factor, high_freq_factor, original_max_position_embeddings)``
    (HF ``_compute_llama3_parameters``): low-frequency components (wavelength
    beyond the original context) are divided by ``factor``, high-frequency
    components pass through, and the band between interpolates smoothly —
    what Llama-3.1/3.2 checkpoints ship in config.json and need at ALL
    positions for HF-parity outputs.
    """
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    if scaling is not None:
        factor, low_f, high_f, original_max = scaling
        wavelen = 2.0 * jnp.pi / freqs
        low_wavelen = original_max / low_f
        high_wavelen = original_max / high_f
        smooth = (original_max / wavelen - low_f) / (high_f - low_f)
        mid = (1.0 - smooth) * freqs / factor + smooth * freqs
        freqs = jnp.where(wavelen > low_wavelen, freqs / factor,
                          jnp.where(wavelen < high_wavelen, freqs, mid))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # (..., S, half)
    angles = jnp.concatenate([angles, angles], axis=-1)        # (..., S, hd)
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray) -> jnp.ndarray:
    """Apply rotary embedding, split-half (HF) convention.

    x: (..., S, n_heads, head_dim); cos/sin: (..., S, head_dim) broadcast over
    the heads axis.
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    return (x.astype(jnp.float32) * cos + rotated.astype(jnp.float32) * sin).astype(x.dtype)
