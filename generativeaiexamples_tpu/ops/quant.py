"""Weight-only int8 quantization for serving.

TPU decode is HBM-bandwidth-bound: every decode step re-reads the full
weight set, so halving weight bytes (bf16 → int8) is a near-2x decode
throughput lever and lets an 8B-class model fit a single v5e chip (~8 GB
weights vs ~16 GB bf16 + KV). The reference gets the same effect from
TRT-LLM's int8/fp8 engines inside the NIM container (ref:
docs/architecture.md:49-61 — quantization is a serving-engine concern, never
exposed to the chain server); here it is an `EngineConfig.quant` knob.

Scheme: **per-channel symmetric int8** over each matmul's contraction axis —
``s = max|w| / 127`` per output column, ``q = round(w / s)``. The matmul
runs in the activation dtype with the int8→bf16 convert fused into the
operand load and the scale applied to the (much smaller) output:

    y = (x @ q.astype(x.dtype)) * s

so the MXU still sees bf16 tiles, HBM sees int8 bytes, and accuracy stays
within per-channel-int8 norms (cosine > 0.999 on logits for trained
checkpoints; see tests/test_quant.py).

`QTensor` is a registered pytree node, so quantized layer stacks ride
`lax.scan` over the layer axis and `jax.jit` argument passing unchanged —
`models.llama._block` calls :func:`matmul`, which dispatches on leaf type;
the same code path serves bf16 and int8 weights (and Gemma, which reuses the
llama block). Quantization happens *after* `shard_params`: elementwise ops
and keepdims reductions propagate the weight's NamedSharding onto ``q`` and
``s``, so TP layouts survive (scales shard on the same output axis).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class QTensor:
    """int8 weights + broadcastable per-channel scale (keepdims layout)."""

    q: jnp.ndarray   # int8, original shape
    s: jnp.ndarray   # f32, original shape with the quantized axis sized 1

    def tree_flatten(self):
        return (self.q, self.s), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)

    @property
    def shape(self):
        return self.q.shape

    @property
    def T(self) -> "QTensor":
        """2-D transpose (tied-embedding unembed: (V, D) row-scales become
        (D, V) column-scales — still constant along the new contraction)."""
        return QTensor(q=self.q.T, s=self.s.T)


def _quantize_impl(w: jnp.ndarray, axis: int) -> QTensor:
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(wf / s), -127, 127).astype(jnp.int8)
    return QTensor(q=q, s=s)


# jit keeps the f32 upcast and the elementwise chain fused — quantizing a
# multi-GB stacked weight eagerly materializes ~5 full-size f32 temporaries
# and OOMs a 16 GB chip on a 3B model. The donating variant additionally
# reuses the source buffer (the engine's load path: the bf16 original is
# dead the moment its QTensor exists).
_quantize_jit = jax.jit(_quantize_impl, static_argnames="axis")
_quantize_donating = jax.jit(_quantize_impl, static_argnames="axis",
                             donate_argnums=0)


def quantize(w: jnp.ndarray, axis: int, donate: bool = False) -> QTensor:
    """Symmetric int8 quantization of ``w`` along ``axis`` (the contraction
    axis of the matmul it will feed, so scales are per-output-channel).
    ``donate=True`` invalidates ``w``'s buffer (load-path memory headroom)."""
    fn = _quantize_donating if donate else _quantize_jit
    return fn(w, axis=axis)


def dequantize(w: QTensor, dtype=jnp.float32) -> jnp.ndarray:
    return (w.q.astype(jnp.float32) * w.s).astype(dtype)


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for plain arrays and QTensors alike (the model's one matmul
    seam). For QTensors the dequant convert fuses into the matmul operand
    load; the scale multiplies the output (out-channel broadcast)."""
    if isinstance(w, QTensor):
        return (x @ w.q.astype(x.dtype)) * w.s.astype(x.dtype)
    return x @ w


def take(w, indices: jnp.ndarray, dtype) -> jnp.ndarray:
    """Embedding-table row gather for plain arrays and QTensors (per-row
    scales gather alongside the rows)."""
    if isinstance(w, QTensor):
        return w.q[indices].astype(dtype) * w.s[indices].astype(dtype)
    return w.astype(dtype)[indices]


# weight name → contraction axis within the *stacked* (L, in, out) layout
_LAYER_AXES = {"wq": 1, "wk": 1, "wv": 1, "wo": 1,
               "w_gate": 1, "w_up": 1, "w_down": 1}


def quantize_params(params: Params, donate: bool = False) -> Params:
    """Quantize a llama-family parameter pytree's matmul weights (norms stay
    high-precision; LoRA adapters are a separate pytree and are never
    quantized). Safe on sharded arrays — run after `shard_params`.

    ``donate=True`` (the engine load path) consumes the source buffers one
    leaf at a time, so peak HBM is original + int8 copy + one leaf — without
    it a 3B bf16 model cannot be quantized in 16 GB, let alone an 8B.
    """
    out = dict(params)
    layers = dict(params["layers"])
    for name, axis in _LAYER_AXES.items():
        # MoE layouts stack an expert axis (L, E, in, out): the stacked-axis
        # table below doesn't apply — leave expert weights high-precision
        # (router stays f32 regardless; see ops/moe.py)
        if name in layers and layers[name].ndim == 3:
            layers[name] = quantize(layers[name], axis=axis, donate=donate)
    out["layers"] = layers
    # embed rows are gathered, so scales are per-row; a tied unembed
    # transposes them into per-output-column scales (see QTensor.T)
    out["embed"] = quantize(params["embed"], axis=1, donate=donate)
    if "lm_head" in params:
        out["lm_head"] = quantize(params["lm_head"], axis=0, donate=donate)
    return out
