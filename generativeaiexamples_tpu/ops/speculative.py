"""Prompt-lookup speculative decoding: on-device n-gram drafting.

The capability TRT-LLM ships inside the reference's NIM container
(speculative decoding; ref docker-compose-nim-ms.yaml:2-28) — redesigned
for the TPU serving engine's fused multi-step decode. RAG outputs quote
their retrieved context, so the cheapest draft model is the request's own
token history: find the latest earlier occurrence of the current suffix
n-gram and propose the tokens that followed it. No draft model, no extra
weights, no host round trip — drafting is a handful of (B, S) vector ops
inside the compiled step, and verification rides the same weight read as
a normal decode step (decode is HBM-bound: a (1+D)-token verify step
costs nearly the same wall clock as a 1-token step).

Acceptance is EXACT-MATCH against the per-slot seeded sample at each
position: position i samples from the model's distribution conditioned on
the accepted prefix with the request's deterministic key for token index
generated+i, and drafts are accepted while they equal those samples. The
emitted stream is therefore token-for-token what sequential decoding with
the same keys would produce — speculation changes wall clock, never
content (modulo the usual batched-matmul rounding of logits).

That exact-match property is ALSO what makes the adaptive width ladder
(engine.spec_adaptive — per-slot draft caps from a trailing acceptance
EMA) token-identical by construction: clamping ``draft_len`` to any cap
in [0, n_draft] only changes HOW MANY drafted positions are verified per
step, never which token each position resolves to — position i's sample
depends only on the accepted prefix and the request's key for index
generated+i, both invariant under the cap. The engine applies the cap as
``dlen = min(dlen, draft_cap)`` before :func:`acceptance`.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def draft_lookup(history: jnp.ndarray, lengths: jnp.ndarray,
                 n_draft: int, ngram: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Draft up to ``n_draft`` tokens per slot from the slot's own history.

    history: (B, S) int32 — token at each absolute position, valid through
    index ``lengths[b]`` INCLUSIVE (the invariant the engine maintains:
    ``history[b, lengths[b]]`` is the token being fed this step).
    Returns (draft (B, n_draft) int32, draft_len (B,) int32): the tokens
    that followed the LATEST earlier occurrence of the current trailing
    ``ngram`` (the occurrence ending at the current position itself is
    excluded), clipped to the known region; draft_len == 0 when the suffix
    n-gram appears nowhere earlier (or the sequence is shorter than the
    n-gram).
    """
    B, S = history.shape
    L = lengths.astype(jnp.int32)                           # (B,)
    # trailing n-gram: positions L-ngram+1 .. L
    g_idx = L[:, None] - (ngram - 1) + jnp.arange(ngram, dtype=jnp.int32)
    gram = jnp.take_along_axis(history, jnp.clip(g_idx, 0, S - 1), axis=1)
    # candidate start p matches iff history[p+i] == gram[i] for all i
    m = jnp.ones((B, S), bool)
    for i in range(ngram):
        m &= jnp.roll(history, -i, axis=1) == gram[:, i:i + 1]
    pos = jnp.arange(S, dtype=jnp.int32)[None]              # (1, S)
    # occurrence fully inside known history, strictly before the current
    # suffix (p + ngram - 1 <= L - 1 excludes it and kills roll wrap-around)
    cand = m & (pos + ngram - 1 <= L[:, None] - 1)
    best = jnp.max(jnp.where(cand, pos, -1), axis=1)        # (B,) latest
    found = (best >= 0) & (L >= ngram - 1)
    d_idx = best[:, None] + ngram + jnp.arange(n_draft, dtype=jnp.int32)
    draft = jnp.take_along_axis(history, jnp.clip(d_idx, 0, S - 1), axis=1)
    # known continuation: positions best+ngram .. L  (history valid thru L)
    avail = L + 1 - (best + ngram)
    draft_len = jnp.where(found, jnp.clip(avail, 0, n_draft), 0)
    return draft.astype(jnp.int32), draft_len.astype(jnp.int32)


def acceptance(sampled: jnp.ndarray, draft: jnp.ndarray,
               draft_len: jnp.ndarray) -> jnp.ndarray:
    """Accepted-prefix length per slot → tokens emitted this step.

    sampled: (B, W) — the per-position samples of a W-wide verify step
    (W = 1 + n_draft); draft: (B, W-1); draft_len: (B,). Position i's
    sample is valid iff every draft before it matched its sample, so the
    step emits ``k+1`` tokens where k is the number of leading matches
    within draft_len. Returns e (B,) in 1..W.
    """
    W = sampled.shape[1]
    if W == 1:
        return jnp.ones(sampled.shape[0], jnp.int32)
    i = jnp.arange(W - 1, dtype=jnp.int32)[None]
    ok = (sampled[:, :-1] == draft) & (i < draft_len[:, None])
    k = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    return (k + 1).astype(jnp.int32)
