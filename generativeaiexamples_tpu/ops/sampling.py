"""Token sampling: greedy, temperature, top-k, top-p — jit-safe, batched.

Implements the OpenAI-API sampling surface the reference's LLM clients expose
(temperature/top_p knobs flow from the chain server request,
ref: RAG/src/chain_server/server.py:104-147 Prompt fields) as pure functions
over logits, usable inside the jitted decode step.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


# bisection depth for the dynamic samplers' top-k/top-p threshold search
# (see _mask_dynamic): resolves the cutoff to range/2^N — enough to
# separate distinct f32 logits in practice
N_BISECT = 26


@dataclass(frozen=True)
class SamplingParams:
    """Static sampling configuration (hashable → usable as a jit static arg)."""

    temperature: float = 1.0
    top_k: int = 0        # 0 = disabled
    top_p: float = 1.0    # 1.0 = disabled

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


def _mask_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, -jnp.inf, logits)


def _mask_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens while cumulative prob (exclusive) < p; the top-1 is always
    # kept so p=0 degrades to greedy instead of masking everything
    keep_sorted = jnp.roll(cum, 1, axis=-1).at[..., 0].set(0.0) < p
    keep_sorted = keep_sorted.at[..., 0].set(True)
    cutoff = jnp.where(keep_sorted, sorted_logits, jnp.inf).min(axis=-1, keepdims=True)
    return jnp.where(logits < cutoff, -jnp.inf, logits)


def sample_logits(rng: jax.Array, logits: jnp.ndarray,
                  params: SamplingParams) -> jnp.ndarray:
    """Sample token ids from (B, vocab) logits. Returns (B,) int32."""
    if params.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / max(params.temperature, 1e-6)
    if params.top_k > 0:
        logits = _mask_top_k(logits, params.top_k)
    if params.top_p < 1.0:
        logits = _mask_top_p(logits, params.top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def grammar_mask(logits: jnp.ndarray, gram_state: jnp.ndarray,
                 budget_left: jnp.ndarray, eos_id: int, table: jnp.ndarray,
                 accept: jnp.ndarray, dist: jnp.ndarray,
                 tok_bytes: jnp.ndarray, tok_lens: jnp.ndarray
                 ) -> jnp.ndarray:
    """Constrained-decoding logit mask, evaluated ON DEVICE inside the fused
    decode step (engine/grammar.py designs the automaton; this is its
    runtime). For every vocab token, walk its byte string through the
    byte-level DFA from each slot's current state: tokens whose walk hits
    the reject sink (state 0) are masked to -inf. EOS is allowed exactly at
    accepting states (and is the ONLY option at a dead end, which is how a
    completed JSON value terminates generation).

    gram_state: (B,) int32 — current DFA state per slot; <= 0 disables
    masking for that slot (unconstrained requests share the program).
    budget_left: (B,) int32 — generation budget remaining AFTER the token
    being sampled; tokens whose post-walk state cannot reach an accept
    state within it (dist, fewest bytes ≥ fewest single-byte tokens) are
    masked, so a constrained generation COMPLETES inside max_tokens
    instead of truncating mid-JSON (a greedy adversarial model would
    otherwise repeat one digit until the budget dies).
    table: (S, 256) int32; accept: (S,) bool; dist: (S,) int32;
    tok_bytes: (V, L) int32; tok_lens: (V,) int32 (-1 = token never
    allowed under a grammar). Cost: L chained (B, V) gathers — bytes, not
    a (S, V) dense table, so a 128k vocab costs ~MBs of traffic per step
    instead of a GB-scale table.
    """
    B, V = logits.shape
    L = tok_bytes.shape[1]
    active = (gram_state > 0)[:, None]                      # (B, 1)
    st = jnp.broadcast_to(jnp.maximum(gram_state, 0)[:, None], (B, V))
    for l in range(L):
        b = tok_bytes[None, :, l]                           # (1, V)
        nxt = table[st, jnp.broadcast_to(b, (B, V))]
        st = jnp.where(tok_lens[None, :] > l, nxt, st)
    ok = (st != 0) & (tok_lens[None, :] > 0)                # (B, V)
    ok &= dist[st] <= budget_left[:, None]
    # EOS exactly at accept states; fail-safe: a state with NO allowed
    # token (shouldn't happen with a byte-complete vocab) unmasks EOS
    # rather than leaving an all -inf row
    ok_eos = accept[jnp.maximum(gram_state, 0)] | ~ok.any(axis=-1)
    ok = ok.at[:, eos_id].set(ok_eos)
    return jnp.where(active & ~ok, -jnp.inf, logits)


def grammar_advance(gram_state: jnp.ndarray, sampled: jnp.ndarray,
                    table: jnp.ndarray, tok_bytes: jnp.ndarray,
                    tok_lens: jnp.ndarray) -> jnp.ndarray:
    """Next DFA state per slot after emitting ``sampled`` (B,) — the walk of
    just the sampled token's bytes. Unconstrained slots (state <= 0) stay
    put."""
    st = jnp.maximum(gram_state, 0)
    bts = tok_bytes[sampled]                                # (B, L)
    lens = tok_lens[sampled]                                # (B,)
    for l in range(tok_bytes.shape[1]):
        nxt = table[st, bts[:, l]]
        st = jnp.where(lens > l, nxt, st)
    return jnp.where(gram_state > 0, st, gram_state)


def _mask_dynamic(lf: jnp.ndarray, temperature: jnp.ndarray,
                  top_k: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Shared per-row temperature/top-k/top-p masking for the dynamic
    samplers (one definition, two categorical-draw strategies).
    lf: (B, V) float32 → scaled+masked logits ready for the draw.

    NO vocab sorts: both filters reduce to a per-row cutoff VALUE found by
    bisection (see masked() below) — a TPU (B, 128k) sort costs ~25 ms and
    even lax.top_k(512) ~5-20 ms (measured on v5e), where ~26 fused
    reduction passes cost ~1 ms. Boundary ties at the cutoff are all
    admitted (>= threshold — measure-zero for continuous logits). Rows
    with neither filter pass through exactly, and a batch with no filters
    skips the search entirely (lax.cond — the pure-temperature serving
    mix never pays it)."""
    B, V = lf.shape
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = lf / safe_t

    def masked(scaled):
        # BISECTION thresholds instead of vocab sorts/top_k: both filters
        # only need a per-row cutoff VALUE, and both objectives — rank
        # count for top-k, probability mass for top-p — are monotone in
        # it. ~N_BISECT reduction passes over (B, V) cost ~1 ms at 128k
        # vocab where a full sort costs ~25 ms and even lax.top_k(512)
        # ~5-20 ms on v5e (measured; TPU sorts are the dominant cost of a
        # sampled decode step, multiplied by W under speculation).
        # Composition matches the sort formulation: top-k resolves first,
        # top-p's mass renormalizes within the k-filtered distribution.
        # Tie behavior at the kth value: ties spanning the boundary keep
        # the smaller set (measure-zero for continuous logits).
        m = jnp.max(scaled, axis=-1)                         # (B,)
        # finite lower bound even when rows carry -inf entries (grammar-
        # masked tokens): an infinite lo would pin every bisection mid at
        # -inf and silently disable the filters. Tokens more than ~100
        # nats below the max carry zero sampling mass, so the bound is
        # exact for the draw.
        lo0 = jnp.maximum(jnp.min(scaled, axis=-1), m - 100.0) - 1.0
        need_k = top_k > 0

        def bisect(pred, lo, hi):
            # invariant: pred(hi) False-side, pred(lo) True-side; returns
            # the converged True-side threshold
            def body(_, carry):
                lo, hi = carry
                mid = 0.5 * (lo + hi)
                ok = pred(mid)                               # (B,) bool
                return (jnp.where(ok, mid, lo), jnp.where(ok, hi, mid))

            lo, hi = jax.lax.fori_loop(0, N_BISECT, body, (lo, hi))
            return lo

        # top-k cutoff: largest t with count(s >= t) >= k
        k_eff = jnp.where(need_k, top_k, 1).astype(jnp.int32)

        def k_pred(t):
            return jnp.sum((scaled >= t[:, None]).astype(jnp.int32),
                           axis=-1) >= k_eff

        thr_k = jax.lax.cond(
            jnp.any(need_k),
            lambda _: jnp.where(need_k, bisect(k_pred, lo0, m + 1.0), lo0),
            lambda _: lo0, operand=None)

        # top-p cutoff within the k-filtered distribution: largest t with
        # mass(s >= t) >= p·mass(k-filtered)
        e = jnp.exp(scaled - m[:, None])                     # (B, V)
        kmask = scaled >= thr_k[:, None]
        z = jnp.sum(jnp.where(kmask, e, 0.0), axis=-1)
        target = jnp.clip(top_p, 0.0, 1.0) * z

        def p_pred(t):
            mass = jnp.sum(jnp.where(kmask & (scaled >= t[:, None]), e,
                                     0.0), axis=-1)
            return mass >= target

        thr_p = jax.lax.cond(
            jnp.any(top_p < 1.0),
            lambda _: jnp.where(top_p < 1.0,
                                bisect(p_pred, lo0, m + 1.0), lo0),
            lambda _: lo0, operand=None)

        # the row maximum always survives (top_p=0 degrades to greedy)
        cut = jnp.minimum(jnp.maximum(thr_k, thr_p), m)[:, None]
        out = jnp.where(scaled >= cut, scaled, -jnp.inf)
        # filterless rows pass through exactly
        need = (need_k | (top_p < 1.0))[:, None]
        return jnp.where(need, out, scaled)

    return jax.lax.cond(jnp.any((top_k > 0) | (top_p < 1.0)), masked,
                        lambda s: s, scaled)


def token_logprob(logits: jnp.ndarray, sampled: jnp.ndarray) -> jnp.ndarray:
    """Log-probability of each sampled token under the model distribution
    (raw logits, temperature-free — what the OpenAI `logprobs` field
    reports). logits: (B, V) any float dtype; sampled: (B,) int32 →
    (B,) float32."""
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)
    picked = jnp.take_along_axis(lf, sampled[:, None].astype(jnp.int32),
                                 axis=-1)[:, 0]
    return picked - lse


def sample_logits_per_slot(keys: jnp.ndarray, logits: jnp.ndarray,
                           temperature: jnp.ndarray, top_k: jnp.ndarray,
                           top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-slot sampling with PER-SLOT PRNG keys — the `seed` surface of
    the serving API. Each slot samples from its own key stream, so a
    seeded request reproduces its exact token sequence regardless of what
    else shares the batch or how the scheduler interleaved it (batch
    composition changes neither the fold_in chain nor the per-row
    categorical draw). Masking semantics are identical to
    :func:`sample_logits_dynamic`.

    keys: (B, 2) uint32 — legacy raw threefry keys, one per slot (already
    folded with the token index by the caller); logits: (B, V);
    temperature/top_k/top_p: (B,).
    """
    lf = logits.astype(jnp.float32)
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)

    def full_path(_):
        scaled = _mask_dynamic(lf, temperature, top_k, top_p)
        sampled = jax.vmap(
            lambda k, row: jax.random.categorical(k, row))(keys, scaled)
        return jnp.where(temperature > 0, sampled.astype(jnp.int32), greedy)

    return jax.lax.cond(jnp.any(temperature > 0), full_path,
                        lambda _: greedy, operand=None)


def sample_logits_dynamic(rng: jax.Array, logits: jnp.ndarray,
                          temperature: jnp.ndarray, top_k: jnp.ndarray,
                          top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-sequence dynamic sampling for the continuous batcher: each slot in
    the decode batch carries its own (temperature, top_k, top_p) — traced
    values, so one compiled program serves mixed requests.

    temperature<=0 ⇒ greedy for that slot. top_k<=0 ⇒ disabled.
    logits: (B, V); temperature/top_k/top_p: (B,).

    The full path costs three (B, V) vocab sorts per decode step (~3 ms at
    V=128k on v5e); when the whole batch is greedy — a common serving mix
    and every deterministic eval — a `lax.cond` skips straight to argmax.
    """
    lf = logits.astype(jnp.float32)
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)

    def full_path(_):
        scaled = _mask_dynamic(lf, temperature, top_k, top_p)
        sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
        return jnp.where(temperature > 0, sampled, greedy)

    return jax.lax.cond(jnp.any(temperature > 0), full_path,
                        lambda _: greedy, operand=None)


def stop_suspect_mask(tok_bytes, tok_lens, stop_bytes) -> "np.ndarray":
    """(V,) bool host-side table for the multi-step decode scan's on-device
    stop *maybe-match* flag: token ``t`` is suspect iff its byte sequence
    shares at least one byte value with any active stop string.

    Sound by construction: any token that contributes even one byte to a
    stop-string match necessarily shares that byte with the stop string,
    so the first contributing token of every possible match is flagged —
    the scan pauses the slot at or before the step where a match could
    complete, and the host's replay (the single source of stop truth)
    confirms or clears it. Deliberately conservative the other way: a
    token sharing a byte without ever matching costs one paused dispatch
    tail, never correctness.

    ``tok_bytes``/``tok_lens`` are the engine's (V, L)/(V,) vocab byte
    table (EngineCore.ensure_token_bytes); ``stop_bytes`` is the set of
    byte values (0..255) appearing in any active stop string. Pure
    numpy — called host-side per distinct stop set, cached by the engine.
    """
    import numpy as np
    tb = np.asarray(tok_bytes)
    tl = np.asarray(tok_lens)
    if not stop_bytes:
        return np.zeros((tb.shape[0],), np.bool_)
    member = np.isin(tb, np.fromiter(stop_bytes, np.int32,
                                     len(stop_bytes)))
    valid = np.arange(tb.shape[1])[None, :] < tl[:, None]
    return np.asarray((member & valid).any(axis=1), np.bool_)
