"""Playground web app: static UI + traced reverse proxy to the chain server.

The reference runs its UI as a separate service pointed at the chain server
(ref: docker-compose `rag-playground` service, APP_SERVERURL/APP_SERVERPORT;
chat_client.py builds `{server_url}/generate` etc. and streams SSE). Same
topology here: `python -m generativeaiexamples_tpu.playground
--chain-url http://host:8081` serves the UI and forwards

    POST /api/generate    → {chain}/generate      (SSE passthrough)
    POST /api/search      → {chain}/search
    GET  /api/documents   → {chain}/documents
    POST /api/documents   → {chain}/documents     (multipart passthrough)
    DELETE /api/documents → {chain}/documents?filename=...

with a fresh UI span's ``traceparent`` injected upstream per request
(ref chat_client.py:43 — every client call is wrapped in a span; the
playground is where traces begin).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Optional

import aiohttp
from aiohttp import web

from generativeaiexamples_tpu.observability import otel

logger = logging.getLogger(__name__)

STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "static")


class PlaygroundServer:
    def __init__(self, chain_url: str, model_name: str = "tpu-llm",
                 speech=None) -> None:
        from generativeaiexamples_tpu.speech.clients import get_speech

        self.chain_url = chain_url.rstrip("/")
        self.model_name = model_name
        # voice loop (ref speech playground: record → ASR → converse → TTS);
        # DisabledSpeech unless APP_SPEECH_SERVER_URL is configured
        self.speech = speech if speech is not None else get_speech()
        self.app = web.Application(client_max_size=128 * 1024 * 1024)
        self.app.add_routes([
            web.get("/", self.index),
            web.get("/health", self.health),
            web.get("/api/config", self.config),
            web.post("/api/generate", self.generate),
            web.post("/api/search", self.search),
            web.get("/api/documents", self.get_documents),
            web.post("/api/documents", self.upload_document),
            web.delete("/api/documents", self.delete_document),
            web.post("/api/transcribe", self.transcribe),
            web.get("/api/transcribe/stream", self.transcribe_stream),
            web.post("/api/speak", self.speak),
            web.static("/static", STATIC_DIR),
        ])
        self.app.cleanup_ctx.append(self._client_ctx)
        self._session: Optional[aiohttp.ClientSession] = None

    async def _client_ctx(self, app):
        self._session = aiohttp.ClientSession()
        yield
        await self._session.close()

    def _span(self, span_name: str):
        """UI span wrapping the whole upstream call (its traceparent rides
        via `otel.inject_traceparent` while the span is current)."""
        return otel.get_tracer("playground").span(span_name)

    # ----------------------------------------------------------------- pages

    async def index(self, request: web.Request) -> web.FileResponse:
        return web.FileResponse(os.path.join(STATIC_DIR, "index.html"))

    async def health(self, request: web.Request) -> web.Response:
        return web.json_response({"message": "Service is up."})

    async def config(self, request: web.Request) -> web.Response:
        return web.json_response({"model_name": self.model_name,
                                  "chain_url": self.chain_url,
                                  "speech": self.speech.available()})

    # ---------------------------------------------------------------- speech

    async def transcribe(self, request: web.Request) -> web.Response:
        """Whole-clip transcription: audio bytes in → {"text"} out (the
        record-button path; ref asr_utils.py transcribe of the captured
        buffer)."""
        if not self.speech.available():
            return web.json_response({"error": "speech disabled"}, status=501)
        audio = await request.read()
        if not audio:
            return web.json_response({"error": "empty audio"}, status=422)
        language = request.query.get("language", "en-US")
        try:
            with self._span("ui.transcribe"):
                text = await asyncio.to_thread(
                    self.speech.transcribe, audio, language)
        except Exception as exc:
            logger.exception("transcription failed")
            return web.json_response({"error": str(exc)}, status=502)
        return web.json_response({"text": text})

    async def transcribe_stream(self, request: web.Request) -> web.WebSocketResponse:
        """Streaming ASR websocket: binary frames = audio chunks, text
        frame "end" = finalize. Sends {"partial"} transcripts as they
        resolve and one {"final"} (ref asr_utils.py:117
        transcribe_streaming's interim/final contract)."""
        from generativeaiexamples_tpu.speech.clients import (
            StreamingTranscriber)

        ws = web.WebSocketResponse()
        await ws.prepare(request)
        if not self.speech.available():
            await ws.send_json({"error": "speech disabled"})
            await ws.close()
            return ws
        transcriber = StreamingTranscriber(
            self.speech, language=request.query.get("language", "en-US"))
        try:
            async for msg in ws:
                if msg.type == aiohttp.WSMsgType.BINARY:
                    partial = await asyncio.to_thread(
                        transcriber.feed, msg.data)
                    if partial is not None:
                        await ws.send_json({"partial": partial})
                elif msg.type == aiohttp.WSMsgType.TEXT:
                    if msg.data == "end":
                        final = await asyncio.to_thread(transcriber.finalize)
                        await ws.send_json({"final": final})
                        break
        except Exception as exc:
            logger.exception("streaming transcription failed")
            try:
                await ws.send_json({"error": str(exc)})
            # tpulint: disable=except-swallow -- client already gone; the
            # ws.close() below is best-effort and the failure was logged above
            except Exception:
                pass
        await ws.close()
        return ws

    async def speak(self, request: web.Request) -> web.Response:
        """TTS: {"text", "voice"?} → audio bytes (the speak-response path;
        ref tts_utils.py:83)."""
        tts_ok = getattr(self.speech, "tts_available",
                         self.speech.available)()
        if not tts_ok:
            # ASR-only stacks (in-tree whisper without an HTTP TTS URL)
            # degrade the speak path cleanly, same contract as DisabledSpeech
            return web.json_response({"error": "speech disabled"}, status=501)
        body = await request.json()
        text = str(body.get("text", "")).strip()
        if not text:
            return web.json_response({"error": "text required"}, status=422)
        try:
            with self._span("ui.speak"):
                audio = await asyncio.to_thread(
                    self.speech.synthesize, text,
                    str(body.get("voice", "default")))
        except Exception as exc:
            logger.exception("synthesis failed")
            return web.json_response({"error": str(exc)}, status=502)
        return web.Response(body=audio, content_type="audio/wav")

    # ----------------------------------------------------------------- proxy

    @staticmethod
    def _error_frames(message: str) -> bytes:
        err = json.dumps({"id": "error", "choices": [{
            "index": 0,
            "message": {"role": "assistant", "content": message},
            "finish_reason": "error"}]})
        return f"data: {err}\n\ndata: [DONE]\n\n".encode()

    async def generate(self, request: web.Request) -> web.StreamResponse:
        body = await request.read()
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        try:
            with self._span("ui.generate"):
                async with self._session.post(
                        f"{self.chain_url}/generate", data=body,
                        headers={"Content-Type": "application/json",
                                 **otel.inject_traceparent({})},
                        timeout=aiohttp.ClientTimeout(total=600)) as upstream:
                    if upstream.status != 200:
                        # surface errors as frames the UI understands — a
                        # bare non-SSE body would render as a silent empty
                        # assistant turn
                        detail = (await upstream.read()).decode(
                            "utf-8", "replace")[:500]
                        await resp.write(self._error_frames(
                            f"chain server error {upstream.status}: "
                            f"{detail}"))
                    else:
                        async for chunk in upstream.content.iter_any():
                            await resp.write(chunk)
        except Exception as exc:
            logger.exception("generate proxy failed")
            await resp.write(self._error_frames(
                f"chain server unreachable: {exc}"))
        await resp.write_eof()
        return resp

    async def _forward_json(self, method: str, path: str, span: str,
                            data: Optional[bytes] = None,
                            params: Optional[dict] = None) -> web.Response:
        try:
            with self._span(span):
                headers = otel.inject_traceparent({})
                if data is not None:
                    headers["Content-Type"] = "application/json"
                async with self._session.request(
                        method, f"{self.chain_url}{path}", data=data,
                        params=params, headers=headers,
                        timeout=aiohttp.ClientTimeout(total=300)) as upstream:
                    payload = await upstream.read()
                    return web.Response(body=payload, status=upstream.status,
                                        content_type="application/json")
        except Exception as exc:
            logger.exception("%s %s proxy failed", method, path)
            return web.json_response(
                {"error": f"chain server unreachable: {exc}"}, status=502)

    async def search(self, request: web.Request) -> web.Response:
        return await self._forward_json("POST", "/search", "ui.search",
                                        data=await request.read())

    async def get_documents(self, request: web.Request) -> web.Response:
        return await self._forward_json("GET", "/documents", "ui.documents")

    async def upload_document(self, request: web.Request) -> web.Response:
        # multipart passthrough: re-wrap the uploaded file for the chain API
        reader = await request.multipart()
        field = await reader.next()
        while field is not None and field.name != "file":
            field = await reader.next()
        if field is None:
            return web.json_response({"error": "field 'file' required"},
                                     status=422)
        payload = await field.read()
        form = aiohttp.FormData()
        form.add_field("file", payload,
                       filename=field.filename or "upload.bin")
        try:
            with self._span("ui.upload"):
                async with self._session.post(
                        f"{self.chain_url}/documents", data=form,
                        headers=otel.inject_traceparent({}),
                        timeout=aiohttp.ClientTimeout(total=600)) as upstream:
                    body = await upstream.read()
                    return web.Response(body=body, status=upstream.status,
                                        content_type="application/json")
        except Exception as exc:
            logger.exception("upload proxy failed")
            return web.json_response(
                {"error": f"chain server unreachable: {exc}"}, status=502)

    async def delete_document(self, request: web.Request) -> web.Response:
        return await self._forward_json(
            "DELETE", "/documents", "ui.delete",
            params={"filename": request.query.get("filename", "")})


def run_playground(chain_url: str, model_name: str = "tpu-llm",
                   host: str = "0.0.0.0", port: int = 8090) -> None:
    server = PlaygroundServer(chain_url, model_name)
    web.run_app(server.app, host=host, port=port, print=None)
