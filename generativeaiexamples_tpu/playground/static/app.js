/* RAG Playground client: SSE chat over fetch, KB management.
   Mirrors the reference ChatClient behaviors (ref chat_client.py):
   predict() streams /generate chunks, search() fetches context documents,
   upload/list/delete manage the knowledge base. */

const state = {
  history: [],          // [{role, content}]
  kb: false,
  busy: false,
};

const $ = (id) => document.getElementById(id);

// ---------------------------------------------------------------- tabs
function showTab(name) {
  $("page-converse").classList.toggle("hidden", name !== "converse");
  $("page-kb").classList.toggle("hidden", name !== "kb");
  $("tab-converse").classList.toggle("active", name === "converse");
  $("tab-kb").classList.toggle("active", name === "kb");
  if (name === "kb") refreshFiles();
}
$("tab-converse").onclick = () => showTab("converse");
$("tab-kb").onclick = () => showTab("kb");

// ------------------------------------------------------------- converse
function addBubble(role, text) {
  const div = document.createElement("div");
  div.className = "bubble " + role;
  div.textContent = text;
  $("chat").appendChild(div);
  $("chat").scrollTop = $("chat").scrollHeight;
  return div;
}

function renderContext(chunks) {
  const list = $("context-list");
  list.innerHTML = "";
  if (!chunks || !chunks.length) {
    list.textContent = "No context retrieved.";
    return;
  }
  for (const c of chunks) {
    const d = document.createElement("div");
    d.className = "ctx-chunk";
    const head = document.createElement("div");
    head.className = "ctx-head";
    head.textContent = `${c.filename || "unknown"} (score ${(+c.score).toFixed(3)})`;
    const body = document.createElement("div");
    body.textContent = c.content;
    d.appendChild(head);
    d.appendChild(body);
    list.appendChild(d);
  }
}

async function streamGenerate(question) {
  const payload = {
    messages: [...state.history, { role: "user", content: question }],
    use_knowledge_base: state.kb,
    max_tokens: 1024,
  };
  const resp = await fetch("/api/generate", {
    method: "POST",
    headers: { "Content-Type": "application/json" },
    body: JSON.stringify(payload),
  });
  const reader = resp.body.getReader();
  const decoder = new TextDecoder();
  const bubble = addBubble("assistant", "");
  let buf = "", full = "";
  for (;;) {
    const { done, value } = await reader.read();
    if (done) break;
    buf += decoder.decode(value, { stream: true });
    const frames = buf.split("\n\n");
    buf = frames.pop();
    for (const frame of frames) {
      if (!frame.startsWith("data: ")) continue;
      const data = frame.slice(6);
      if (data === "[DONE]") continue;
      try {
        const chunk = JSON.parse(data);
        const content = chunk.choices?.[0]?.message?.content || "";
        if (content) {
          full += content;
          bubble.textContent = full;
          $("chat").scrollTop = $("chat").scrollHeight;
        }
      } catch (e) { /* partial frame */ }
    }
  }
  return full;
}

$("chat-form").onsubmit = async (ev) => {
  ev.preventDefault();
  const question = $("msg").value.trim();
  if (!question || state.busy) return;
  state.busy = true;
  $("send").disabled = true;
  $("msg").value = "";
  addBubble("user", question);
  try {
    if (state.kb) {
      fetch("/api/search", {
        method: "POST",
        headers: { "Content-Type": "application/json" },
        body: JSON.stringify({ query: question, top_k: 4 }),
      }).then((r) => r.json()).then((d) => renderContext(d.chunks)).catch(() => {});
    }
    const answer = await streamGenerate(question);
    state.history.push({ role: "user", content: question });
    state.history.push({ role: "assistant", content: answer });
    speakText(answer);
  } catch (e) {
    addBubble("assistant", "Error: " + e);
  } finally {
    state.busy = false;
    $("send").disabled = false;
  }
};

$("use-kb").onchange = (ev) => { state.kb = ev.target.checked; };
$("clear-history").onclick = () => {
  state.history = [];
  $("chat").innerHTML = "";
};
$("toggle-context").onclick = () => {
  const panel = $("context-panel");
  panel.classList.toggle("hidden");
  $("toggle-context").textContent =
    panel.classList.contains("hidden") ? "Show Context" : "Hide Context";
};

// ------------------------------------------------------------------- kb
async function refreshFiles() {
  const rows = $("file-rows");
  try {
    const resp = await fetch("/api/documents");
    const data = await resp.json();
    rows.innerHTML = "";
    const docs = data.documents || [];
    if (!docs.length) {
      rows.innerHTML = "<tr><td colspan=2>No Files uploaded</td></tr>";
      return;
    }
    for (const name of docs) {
      const tr = document.createElement("tr");
      const td = document.createElement("td");
      td.textContent = name;
      const act = document.createElement("td");
      const btn = document.createElement("button");
      btn.textContent = "Delete";
      btn.onclick = async () => {
        const r = await fetch(
          "/api/documents?filename=" + encodeURIComponent(name),
          { method: "DELETE" });
        const d = await r.json();
        $("kb-message").textContent =
          d.deleted ? `Deleted ${name}` : `Could not delete ${name}`;
        refreshFiles();
      };
      act.appendChild(btn);
      tr.appendChild(td);
      tr.appendChild(act);
      rows.appendChild(tr);
    }
  } catch (e) {
    rows.innerHTML = "<tr><td colspan=2>Error loading files</td></tr>";
  }
}

$("upload-form").onsubmit = async (ev) => {
  ev.preventDefault();
  const files = $("file-input").files;
  if (!files.length) return;
  for (const file of files) {
    const form = new FormData();
    form.append("file", file, file.name);
    try {
      const resp = await fetch("/api/documents", { method: "POST", body: form });
      const data = await resp.json();
      $("kb-message").textContent = data.message || data.error || "";
    } catch (e) {
      $("kb-message").textContent = "Upload failed: " + e;
    }
  }
  $("file-input").value = "";
  refreshFiles();
};

// --------------------------------------------------------------- speech
// Voice loop parity with the reference speech playground (record -> ASR ->
// converse -> TTS, ref rag_playground/speech/{asr_utils,tts_utils}.py):
// hold the mic button to stream audio chunks over the /api/transcribe/stream
// websocket (live partial transcripts land in the input box); release to
// finalize and submit. "Speak replies" plays each answer via /api/speak.
const speech = { recorder: null, ws: null, wantStop: false };

function micSupported() {
  return navigator.mediaDevices && window.MediaRecorder;
}

async function startRecording() {
  const stream = await navigator.mediaDevices.getUserMedia({ audio: true });
  const proto = location.protocol === "https:" ? "wss" : "ws";
  const ws = new WebSocket(`${proto}://${location.host}/api/transcribe/stream`);
  ws.onmessage = (ev) => {
    try {
      const msg = JSON.parse(ev.data);
      if (msg.partial !== undefined) $("msg").value = msg.partial;
      if (msg.final !== undefined) {
        $("msg").value = msg.final;
        if (msg.final.trim()) $("chat-form").requestSubmit();
      }
      if (msg.error) $("msg").placeholder = "ASR error: " + msg.error;
    } catch (e) { /* non-JSON frame */ }
  };
  const recorder = new MediaRecorder(stream);
  // chunks recorded before the ws finishes connecting are buffered, not
  // dropped — otherwise the first words of the utterance never reach ASR
  const queue = [];
  let ended = false;
  const flush = () => {
    while (queue.length) ws.send(queue.shift());
    if (ended) ws.send("end");
  };
  ws.onopen = flush;
  let chain = Promise.resolve();   // keeps chunk order across async decodes
  recorder.ondataavailable = (ev) => {
    if (!ev.data.size) return;
    chain = chain.then(async () => {
      queue.push(await ev.data.arrayBuffer());
      if (ws.readyState === WebSocket.OPEN) flush();
    });
  };
  recorder.onstop = () => {
    chain = chain.then(() => {
      ended = true;
      if (ws.readyState === WebSocket.OPEN) flush();
    });
    stream.getTracks().forEach((t) => t.stop());
  };
  recorder.start(500);            // 500 ms chunks stream while talking
  speech.recorder = recorder;
  speech.ws = ws;
  // released while the permission prompt was up: stop immediately —
  // the mic must never stay live past the button release
  if (speech.wantStop) stopRecording();
}

function stopRecording() {
  speech.wantStop = true;
  if (speech.recorder && speech.recorder.state !== "inactive")
    speech.recorder.stop();
  $("mic").classList.remove("recording");
}

async function speakText(text) {
  if (!$("speak-replies").checked || !text) return;
  try {
    const resp = await fetch("/api/speak", {
      method: "POST",
      headers: { "Content-Type": "application/json" },
      body: JSON.stringify({ text }),
    });
    if (!resp.ok) return;
    const url = URL.createObjectURL(await resp.blob());
    const audio = new Audio(url);
    audio.onended = () => URL.revokeObjectURL(url);
    audio.play().catch(() => {});
  } catch (e) { /* TTS is best-effort */ }
}

function initSpeech(enabled) {
  if (!enabled || !micSupported()) return;
  $("mic").classList.remove("hidden");
  $("speak-wrap").classList.remove("hidden");
  const mic = $("mic");
  // pointer events cover mouse AND touch (touch devices fire no mouseup
  // on hold-release: the mic would stay live forever with mouse handlers)
  mic.onpointerdown = (ev) => {
    ev.preventDefault();
    speech.wantStop = false;
    mic.classList.add("recording");
    startRecording().catch((e) => {
      mic.classList.remove("recording");
      $("msg").placeholder = "mic error: " + e;
    });
  };
  mic.onpointerup = stopRecording;
  mic.onpointercancel = stopRecording;
  mic.onmouseleave = stopRecording;
}

// ----------------------------------------------------------------- init
fetch("/api/config").then((r) => r.json()).then((cfg) => {
  $("model-name").textContent = cfg.model_name || "";
  initSpeech(!!cfg.speech);
}).catch(() => {});
