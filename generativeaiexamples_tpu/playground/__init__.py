"""RAG Playground — the in-tree web UI over the chain-server API.

Counterpart of the reference's L7 layer (ref: RAG/src/rag_playground/default —
gradio Blocks pages `converse.py` and `kb.py` talking to the chain server via
`chat_client.py`). Re-designed dependency-free: a small aiohttp app serves a
static single-page UI (vanilla JS, SSE over fetch) and proxies `/api/*` to
the chain server, injecting W3C ``traceparent`` headers on every upstream
call the way the reference's ChatClient does (ref chat_client.py:43,63-171)
so one trace spans UI → chain server → engine.
"""

from generativeaiexamples_tpu.playground.app import PlaygroundServer, run_playground  # noqa: F401
