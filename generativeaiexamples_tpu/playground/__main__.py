"""CLI entry: run the RAG Playground web UI.

    python -m generativeaiexamples_tpu.playground \
        [--chain-url http://localhost:8081] [--port 8090]

Counterpart of the reference's `python -m frontend` service (ref
rag_playground/default/__main__.py: --host/--port args, APP_SERVERURL/
APP_SERVERPORT env pointing at the chain server).
"""

from __future__ import annotations

import argparse
import logging
import os

from generativeaiexamples_tpu.playground.app import run_playground


def main() -> None:
    default_chain = os.environ.get("APP_SERVERURL", "http://localhost")
    default_port = os.environ.get("APP_SERVERPORT", "8081")
    if not default_chain.startswith("http"):
        default_chain = "http://" + default_chain
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--chain-url",
                        default=f"{default_chain}:{default_port}",
                        help="chain server base URL")
    parser.add_argument("--model-name", default=os.environ.get(
        "APP_MODELNAME", "tpu-llm"))
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8090)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    run_playground(args.chain_url, args.model_name, host=args.host,
                   port=args.port)


if __name__ == "__main__":
    main()
