"""KV-handoff wire codecs: the binary zero-copy frame and the JSON-base64
compat form.

The disaggregated route (PR 6) ships a prefilled slot's KV pages between
engine workers. The original wire was JSON-base64 — every byte inflated
4/3 by base64 AND copied twice (bytes → b64 str → JSON str), ~60 MB per
512-token prompt on 3B models. This module is the transport fix (ROADMAP
item 1): a framed octet-stream body whose array payloads are written and
read as RAW bytes —

    ``KVW1`` magic | u32 header length | JSON header | aligned segments

The header carries everything that is not bulk data: the payload's scalar
passthrough (geometry, sampling state, SLO class, the usage plane's
``tenant``, grammar state — every non-array key, verbatim) plus one
descriptor per array segment: dtype, shape, byte offset/length into the
segment area, and a crc32. Decoding never copies a segment: each array is
an ``np.frombuffer`` view into the request body (read-only — importers
must tolerate that; the engine's device upload does). Encoding writes each
array's buffer once, with no base64 and no per-byte JSON walk.

Integrity is explicit, not hoped for: the header length-prefixes every
segment and carries its crc32, and :func:`decode_kv_frames` verifies both
BEFORE the payload reaches ``validate_handoff`` — a truncated or garbled
body is a loud :class:`KVWireError` (HTTP 400 at the serving layer), never
silently-scattered garbage KV. This matters more on the binary wire than
it did on JSON: flipped bits in a base64 body usually break the JSON
parse, while flipped bits in a raw segment would otherwise still be a
shape-valid buffer.

Deliberately numpy-only (no jax import): the routing frontend
(server/failover.py) lives in chain-server processes and transcodes
between wire forms for mixed-version pools — it must not drag the engine
stack in. ``np.ascontiguousarray`` materializes device (jax) arrays via
``__array__`` without this module ever naming jax, which is how the
engine's device-native export payloads meet the wire.
"""

from __future__ import annotations

import base64
import json
import struct
import zlib
from typing import Any, Dict, Optional

import numpy as np

# array-valued keys of a handoff payload; everything else is scalar
# passthrough (the contract encode_kv_payload always had)
PAYLOAD_ARRAYS = ("k", "v", "k_s", "v_s")

# int-list keys the BINARY frame packs as narrow integer segments instead
# of JSON text (a 512-token prompt is ~2.5 KB of ", 123" in the header vs
# ~1 KB of uint16) — decoded back to plain Python lists, so consumers
# never see the difference. The JSON wire keeps them as scalar
# passthrough (compat form, byte-stable with PR 6).
_PACKED_INT_LISTS = ("prompt_ids",)

# the binary frame's content type: /v1/kv/prefill serves it when the
# client's Accept names it; /v1/kv/handoff accepts it as a request body.
# Workers advertise support via the /health body's ``kv_wire`` list, so a
# router never sends a frame to a worker that would 400 it.
KV_FRAMES_CONTENT_TYPE = "application/x-kv-frames"

_MAGIC = b"KVW1"
_PREFIX = struct.Struct("<4sI")     # magic, header byte length
_ALIGN = 64                         # segment alignment (dtype-safe views)
_MAX_HEADER = 16 * 1024 * 1024      # a header is metadata, never bulk data


class KVWireError(ValueError):
    """A wire body that cannot be decoded safely: truncated, misframed, or
    failing its crc32. The serving layer answers 400 — loud, before any
    byte reaches the pool."""


def _np_dtype(name: str) -> np.dtype:
    """np.dtype for a payload's dtype string, including the ml_dtypes
    extension types numpy cannot resolve by name (bfloat16)."""
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


# --------------------------------------------------------------- JSON form

def encode_kv_payload(payload: dict) -> dict:
    """Host KV-handoff payload → JSON-safe dict: arrays become {b64,
    dtype, shape} triples, everything else passes through. The passthrough
    is a contract: sampling state, SLO class, grammar state, and the usage
    plane's ``tenant`` identity all ride the wire as plain scalar keys.
    This is the COMPAT wire — 4/3 inflation and two byte copies per array;
    new routes negotiate :func:`encode_kv_frames` instead."""
    out = {}
    for key, value in payload.items():
        if key in PAYLOAD_ARRAYS and value is not None:
            arr = np.ascontiguousarray(value)
            out[key] = {"b64": base64.b64encode(arr.tobytes()).decode("ascii"),
                        "dtype": str(arr.dtype),
                        "shape": list(arr.shape)}
        else:
            out[key] = value
    return out


def decode_kv_payload(wire: dict) -> dict:
    """Inverse of :func:`encode_kv_payload`."""
    out = {}
    for key, value in wire.items():
        if (key in PAYLOAD_ARRAYS and isinstance(value, dict)
                and "b64" in value):
            buf = base64.b64decode(value["b64"])
            out[key] = np.frombuffer(
                buf, dtype=_np_dtype(value["dtype"])).reshape(value["shape"])
        else:
            out[key] = value
    return out


# ------------------------------------------------------------- binary form

def encode_kv_frames(payload: Dict[str, Any]) -> bytes:
    """Handoff payload → one framed octet-stream body (see module doc).

    Array values may be numpy or device (jax) arrays — each is
    materialized contiguously exactly once and its bytes written RAW into
    the segment area. Non-array keys must be JSON-serializable (they
    always were — they rode the JSON wire's passthrough)."""
    metas = []
    segments = []
    offset = 0
    values = {key: payload.get(key) for key in PAYLOAD_ARRAYS}
    for key in _PACKED_INT_LISTS:
        ids = payload.get(key)
        if ids is None:
            continue
        arr = np.asarray(ids)
        # narrowest lossless integer dtype: token ids are non-negative
        # and bounded by the vocab, so uint16 covers most models
        values[key] = arr.astype(
            np.uint16 if arr.size == 0 or (arr.min() >= 0
                                           and arr.max() < 1 << 16)
            else np.int32)
    for key in (*PAYLOAD_ARRAYS, *_PACKED_INT_LISTS):
        value = values.get(key)
        if value is None:
            continue
        # one host materialization per array (THE device→host copy for
        # device-native export payloads), then tobytes — not the buffer
        # protocol, because extension dtypes (bfloat16) have no PEP-3118
        # format; one memcpy per array is noise next to the base64 4/3
        # inflation + per-byte JSON walk this replaces
        arr = np.ascontiguousarray(value)
        data = arr.tobytes()
        pad = (-offset) % _ALIGN
        offset += pad
        metas.append({"key": key, "dtype": str(arr.dtype),
                      "shape": list(arr.shape), "off": offset,
                      "nbytes": len(data),
                      "crc32": zlib.crc32(data) & 0xFFFFFFFF})
        segments.append((pad, data))
        offset += len(data)
    header = json.dumps({
        "v": 1,
        "meta": {key: value for key, value in payload.items()
                 if key not in PAYLOAD_ARRAYS
                 and not (key in _PACKED_INT_LISTS
                          and values.get(key) is not None)},
        "arrays": metas,
        "data_bytes": offset,
    }).encode("utf-8")
    parts = [_PREFIX.pack(_MAGIC, len(header)), header]
    for pad, data in segments:
        if pad:
            parts.append(b"\x00" * pad)
        parts.append(data)
    return b"".join(parts)


def is_kv_frames(body: bytes, content_type: str = "") -> bool:
    """Cheap sniff: does ``body`` carry the binary frame? Content type
    wins when present; the magic covers clients that forgot to set it."""
    if content_type and content_type.split(";")[0].strip().lower() \
            == KV_FRAMES_CONTENT_TYPE:
        return True
    return bytes(body[:4]) == _MAGIC


def _read_header(body) -> tuple:
    view = memoryview(body)
    if len(view) < _PREFIX.size:
        raise KVWireError(
            f"kv frame truncated: {len(view)} bytes is shorter than the "
            f"{_PREFIX.size}-byte frame prefix")
    magic, hlen = _PREFIX.unpack_from(view, 0)
    if magic != _MAGIC:
        raise KVWireError(f"kv frame magic mismatch: {bytes(magic)!r}")
    if not 0 < hlen <= _MAX_HEADER:
        raise KVWireError(f"kv frame header length {hlen} outside bounds")
    if len(view) < _PREFIX.size + hlen:
        raise KVWireError(
            f"kv frame truncated inside the header: body holds "
            f"{len(view)} bytes, header claims {hlen}")
    try:
        header = json.loads(bytes(view[_PREFIX.size:_PREFIX.size + hlen]))
    except ValueError as exc:
        raise KVWireError(f"kv frame header is not JSON: {exc}")
    if not isinstance(header, dict) or "arrays" not in header:
        raise KVWireError("kv frame header missing its array table")
    return header, view, _PREFIX.size + hlen


def peek_kv_frames_meta(body) -> Dict[str, Any]:
    """The frame's scalar passthrough WITHOUT touching (or validating) the
    segment area — the router reads n_pages/tenant for span attributes
    off a multi-MB body it otherwise just relays."""
    header, _, _ = _read_header(body)
    meta = header.get("meta")
    return dict(meta) if isinstance(meta, dict) else {}


def decode_kv_frames(body, verify: bool = True) -> Dict[str, Any]:
    """Framed body → handoff payload dict. Array values are READ-ONLY
    ``np.frombuffer`` views into ``body`` — zero copies; the caller owns
    keeping ``body`` alive as long as the arrays (numpy holds a reference,
    so a plain ``bytes`` body takes care of itself).

    Every segment is bounds-checked against the real body length and (by
    default) crc32-verified BEFORE anything is returned — truncation and
    bit corruption both raise :class:`KVWireError` here, upstream of
    ``validate_handoff``'s geometry checks."""
    header, view, data_start = _read_header(body)
    data = view[data_start:]
    claimed = int(header.get("data_bytes", -1))
    if claimed != len(data):
        raise KVWireError(
            f"kv frame truncated: segment area holds {len(data)} bytes, "
            f"header claims {claimed}")
    out: Dict[str, Any] = dict(header.get("meta") or {})
    for desc in header["arrays"]:
        key = desc.get("key")
        if key not in PAYLOAD_ARRAYS and key not in _PACKED_INT_LISTS:
            raise KVWireError(f"kv frame names unknown array {key!r}")
        off, nbytes = int(desc["off"]), int(desc["nbytes"])
        if off < 0 or nbytes < 0 or off + nbytes > len(data):
            raise KVWireError(
                f"kv frame segment {key!r} [{off}:{off + nbytes}] falls "
                f"outside the {len(data)}-byte segment area")
        seg = data[off:off + nbytes]
        if verify:
            crc = zlib.crc32(seg) & 0xFFFFFFFF
            if crc != int(desc.get("crc32", -1)):
                raise KVWireError(
                    f"kv frame segment {key!r} failed its crc32 "
                    f"({crc:#010x} != {int(desc.get('crc32', -1)):#010x}) "
                    f"— corrupted in transit")
        dtype = _np_dtype(str(desc["dtype"]))
        shape = tuple(int(s) for s in desc["shape"])
        want = int(np.prod(shape)) * dtype.itemsize if shape else \
            dtype.itemsize
        if want != nbytes:
            raise KVWireError(
                f"kv frame segment {key!r}: {nbytes} bytes cannot hold "
                f"shape {shape} of {dtype}")
        arr = np.frombuffer(seg, dtype=dtype).reshape(shape)
        # packed int lists come back as the plain Python lists they were
        # — consumers (validate_handoff, the scheduler, transcoding)
        # never see the packing
        out[key] = arr.tolist() if key in _PACKED_INT_LISTS else arr
    return out


def transcode_to_json(body) -> dict:
    """Binary frame → the JSON-base64 wire dict, for relaying a new
    prefill worker's payload to an old decode worker (router compat path).
    Validates the frame on the way — a router must not launder a corrupt
    frame into a shape-valid JSON body."""
    return encode_kv_payload(decode_kv_frames(body))


def encode_for_wire(payload: Dict[str, Any], binary: bool):
    """One switch for the serving layer: returns ``(body_bytes,
    content_type)`` in the negotiated form."""
    if binary:
        return encode_kv_frames(payload), KV_FRAMES_CONTENT_TYPE
    return (json.dumps(encode_kv_payload(payload)).encode("utf-8"),
            "application/json")
