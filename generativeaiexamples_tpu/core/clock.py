"""Injectable clock — the ONE time source scheduler/QoS/KV-tier policy
code reads, so the trace-replay simulator (ops/simulate.py) can drive the
real policy objects on a virtual clock.

Live serving pays one attribute read + one function call over the direct
``time.*`` call; the win is that every duration, deadline, quota refill,
and recency score in policy code is computed from a clock the simulator
owns. A tpulint rule (``clock-injection``, analysis/rules.py) keeps direct
``time.time()``/``time.monotonic()``/``time.perf_counter()`` calls out of
the policy modules so the seam cannot silently erode.

Three faces, matching the codebase's existing clock discipline:

  * :func:`mono`  — interval arithmetic (quota buckets, recency, cadence);
  * :func:`perf`  — request-timeline stamps and deadline math (the
    ``Request`` dataclass's native clock);
  * :func:`wall`  — reported timestamps ONLY, never subtracted.

Under the default :class:`SystemClock` these are exactly
``time.monotonic`` / ``time.perf_counter`` / ``time.time``. A
:class:`VirtualClock` pins mono == perf (one virtual timeline) and offsets
wall from a fixed epoch, so replayed runs are deterministic and
reproducible independent of host speed.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager


class SystemClock:
    """The live default: thin pass-throughs to the stdlib clocks."""

    virtual = False

    def mono(self) -> float:
        return time.monotonic()

    def perf(self) -> float:
        return time.perf_counter()

    def wall(self) -> float:
        return time.time()


class VirtualClock:
    """Simulator-owned timeline: time moves only when :meth:`advance` is
    called. ``mono`` and ``perf`` read the SAME value — in a simulated
    run there is exactly one notion of now — and ``wall`` is that value
    plus a fixed epoch so trace records still carry plausible absolute
    stamps."""

    virtual = True

    def __init__(self, start: float = 0.0, wall_epoch: float = 1.7e9):
        self._now = float(start)
        self._wall_epoch = float(wall_epoch)

    def mono(self) -> float:
        return self._now

    def perf(self) -> float:
        return self._now

    def wall(self) -> float:
        return self._wall_epoch + self._now

    def advance(self, dt: float) -> float:
        """Move virtual time forward by ``dt`` seconds (never backward —
        a negative step would violate every monotonic-clock assumption
        the policy code makes)."""
        if dt > 0:
            self._now += float(dt)
        return self._now

    def advance_to(self, t: float) -> float:
        if t > self._now:
            self._now = float(t)
        return self._now


_active: SystemClock = SystemClock()
_install_lock = threading.Lock()


def active():
    """The currently installed clock object (SystemClock unless a
    simulator installed a virtual one)."""
    return _active


def is_virtual() -> bool:
    return getattr(_active, "virtual", False)


def mono() -> float:
    return _active.mono()


def perf() -> float:
    return _active.perf()


def wall() -> float:
    return _active.wall()


def install(clock) -> None:
    """Swap the process-wide clock. Simulator-only: live servers never
    call this; tests restore via :func:`reset` / :func:`use`."""
    global _active
    with _install_lock:
        _active = clock


def reset() -> None:
    install(SystemClock())


@contextmanager
def use(clock):
    """Scoped install — the simulator's run loop and tests wrap episodes
    in this so a crashed run cannot leak virtual time into live code."""
    prev = _active
    install(clock)
    try:
        yield clock
    finally:
        install(prev)
