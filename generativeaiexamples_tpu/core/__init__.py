"""Core foundation: configuration, logging, metrics, tracing."""

from generativeaiexamples_tpu.core.config import (  # noqa: F401
    AppConfig,
    EmbeddingConfig,
    EngineConfig,
    LLMConfig,
    RankingConfig,
    RetrieverConfig,
    TextSplitterConfig,
    VectorStoreConfig,
    configfield,
    get_config,
    load_config,
)
