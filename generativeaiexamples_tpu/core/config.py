"""Typed application configuration with YAML/JSON file loading and env overlay.

TPU-native re-design of the reference's ConfigWizard flag system
(ref: RAG/src/chain_server/configuration_wizard.py:90-283 — dataclass-wizard
based loader with recursive ``APP_*`` env-var override and auto-generated help;
schema in RAG/src/chain_server/configuration.py:21-204).

Semantics preserved:
  * nested frozen dataclasses describe the schema;
  * config file comes from ``APP_CONFIG_FILE`` (YAML or JSON); missing file
    means "all defaults" (ref: utils.py:180-186, default ``/dev/null``);
  * every leaf field can be overridden by ``APP_<SECTION>_<FIELD>`` env vars,
    computed recursively from the schema
    (ref: configuration_wizard.py:164-234);
  * ``print_help`` enumerates every env var with its help text
    (ref: configuration_wizard.py:95-162).

Implementation is new: plain ``dataclasses`` + a small recursive loader —
no dataclass-wizard dependency.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import typing
from dataclasses import MISSING, dataclass, field, fields, is_dataclass
from functools import lru_cache
from typing import Any, Dict, Mapping, Optional, TextIO

import yaml

logger = logging.getLogger(__name__)

ENV_PREFIX = "APP"
_HELP_KEY = "__config_help__"

# ---------------------------------------------------------------------------
# Shared outbound-HTTP timeout
# ---------------------------------------------------------------------------

# tpulint's net-timeout rule requires every outbound HTTP call to carry an
# explicit timeout; this is the one default they share, so operators tune a
# single knob instead of hunting per-site constants.
DEFAULT_HTTP_TIMEOUT_S = 30.0


def http_timeout(default: Optional[float] = None) -> float:
    """The process-wide outbound-HTTP timeout in seconds.

    A call site's explicit ``default`` (its declared budget — a 10-minute
    SSE generation vs. a 2-second health probe) always wins;
    ``APP_HTTP_TIMEOUT_S`` replaces :data:`DEFAULT_HTTP_TIMEOUT_S` only
    for sites with no opinion. The env knob tuning probe timeouts must
    never silently clamp a long streaming generation mid-reply.
    """
    if default is not None:
        return default
    raw = os.environ.get(f"{ENV_PREFIX}_HTTP_TIMEOUT_S", "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logger.warning("ignoring non-numeric %s_HTTP_TIMEOUT_S=%r",
                           ENV_PREFIX, raw)
    return DEFAULT_HTTP_TIMEOUT_S


def env_float(name: str, default: float) -> float:
    """A float env knob with a warn-and-default parse (the robustness
    plane's APP_WATCHDOG_*/APP_ROUTER_* knobs share this one reader)."""
    raw = os.environ.get(name, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            logger.warning("ignoring non-numeric %s=%r", name, raw)
    return default


def env_int(name: str, default: int) -> int:
    """An int env knob with a warn-and-default parse (the usage plane's
    APP_USAGE_MAX_TENANTS cardinality cap reads through this)."""
    raw = os.environ.get(name, "")
    if raw:
        try:
            return int(raw)
        except ValueError:
            logger.warning("ignoring non-numeric %s=%r", name, raw)
    return default


def configfield(name: str, *, default: Any = MISSING, default_factory: Any = MISSING,
                help_txt: str = "") -> Any:
    """Declare a documented config field (ref: configuration_wizard.py:42-63).

    ``name`` is the canonical file/env key (lowercase, may differ from the
    attribute name); ``help_txt`` feeds the env-var help printer.
    """
    meta = {"name": name, _HELP_KEY: help_txt}
    if default_factory is not MISSING:
        return field(default_factory=default_factory, metadata=meta)
    if default is MISSING:
        return field(metadata=meta)
    return field(default=default, metadata=meta)


def _field_key(f: dataclasses.Field) -> str:
    return f.metadata.get("name", f.name)


def _coerce(value: Any, ftype: Any) -> Any:
    """Coerce a string (from env) or YAML scalar into the annotated type."""
    origin = typing.get_origin(ftype)
    if origin is typing.Union:  # Optional[...]
        args = [a for a in typing.get_args(ftype) if a is not type(None)]
        if value is None:
            return None
        return _coerce(value, args[0]) if args else value
    if ftype is bool:
        if isinstance(value, bool):
            return value
        return str(value).strip().lower() in ("1", "true", "yes", "on")
    if ftype is int:
        return int(value)
    if ftype is float:
        return float(value)
    if ftype is str:
        return str(value)
    if origin in (list, tuple):
        if isinstance(value, str):
            value = json.loads(value)
        return list(value) if origin is list else tuple(value)
    if origin is dict:
        if isinstance(value, str):
            value = json.loads(value)
        return dict(value)
    return value


def _from_dict(cls: type, data: Mapping[str, Any], env_path: str) -> Any:
    """Recursively build ``cls`` from ``data`` with env overlay at each leaf.

    Env var for a leaf is ``APP_<PATH>_<FIELD>`` where path components are the
    uppercase canonical field keys (ref: configuration_wizard.py:164-234).
    """
    kwargs: Dict[str, Any] = {}
    for f in fields(cls):
        key = _field_key(f)
        env_name = f"{env_path}_{key.upper()}" if env_path else key.upper()
        if is_dataclass(f.type if isinstance(f.type, type) else _resolve_type(cls, f)):
            sub_cls = f.type if isinstance(f.type, type) else _resolve_type(cls, f)
            sub_data = data.get(key, {}) if isinstance(data, Mapping) else {}
            kwargs[f.name] = _from_dict(sub_cls, sub_data or {}, env_name)
            continue
        env_val = os.environ.get(env_name)
        if env_val is not None:
            kwargs[f.name] = _coerce(env_val, _resolve_type(cls, f))
        elif isinstance(data, Mapping) and key in data:
            kwargs[f.name] = _coerce(data[key], _resolve_type(cls, f))
        elif f.default is not MISSING:
            kwargs[f.name] = f.default
        elif f.default_factory is not MISSING:  # type: ignore[misc]
            kwargs[f.name] = f.default_factory()  # type: ignore[misc]
        else:
            raise ValueError(f"missing required config field {env_name}")
    return cls(**kwargs)


@lru_cache(maxsize=None)
def _type_hints(cls: type) -> Dict[str, Any]:
    return typing.get_type_hints(cls)


def _resolve_type(cls: type, f: dataclasses.Field) -> Any:
    t = _type_hints(cls).get(f.name, f.type)
    return t


def _iter_env_vars(cls: type, env_path: str):
    for f in fields(cls):
        key = _field_key(f)
        env_name = f"{env_path}_{key.upper()}" if env_path else key.upper()
        ftype = _resolve_type(cls, f)
        if is_dataclass(ftype):
            yield from _iter_env_vars(ftype, env_name)
        else:
            default = f.default if f.default is not MISSING else (
                f.default_factory() if f.default_factory is not MISSING else None)  # type: ignore[misc]
            yield env_name, ftype, default, f.metadata.get(_HELP_KEY, "")


# ---------------------------------------------------------------------------
# Schema (ref: RAG/src/chain_server/configuration.py)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class VectorStoreConfig:
    """Vector store settings (ref: configuration.py:21-46)."""

    name: str = configfield("name", default="tpu", help_txt="Vector store backend: tpu|milvus|pgvector.")
    url: str = configfield("url", default="", help_txt="Remote vector DB URL (unused for the in-proc TPU store).")
    nlist: int = configfield("nlist", default=64, help_txt="IVF: number of coarse cells (ref GPU_IVF_FLAT nlist, configuration.py:42).")
    nprobe: int = configfield("nprobe", default=16, help_txt="IVF: cells probed per query (ref configuration.py:44).")
    index_type: str = configfield("index_type", default="exact", help_txt="Index kind: exact|ivf.")


@dataclass(frozen=True)
class LLMConfig:
    """LLM engine/client settings (ref: configuration.py:48-84)."""

    model_name: str = configfield("model_name", default="llama3-8b-instruct", help_txt="Served model name.")
    server_url: str = configfield("server_url", default="", help_txt="Remote OpenAI-compatible server; empty = in-process TPU engine.")
    model_engine: str = configfield("model_engine", default="tpu", help_txt="Engine kind: tpu|openai-compat.")


@dataclass(frozen=True)
class TextSplitterConfig:
    """Splitter settings (ref: configuration.py:86-112)."""

    model_name: str = configfield("model_name", default="byte-bpe", help_txt="Tokenizer used to count tokens while chunking.")
    chunk_size: int = configfield("chunk_size", default=510, help_txt="Chunk size in tokens (ref default 510, configuration.py:90).")
    chunk_overlap: int = configfield("chunk_overlap", default=200, help_txt="Chunk overlap in tokens (ref default 200).")


@dataclass(frozen=True)
class EmbeddingConfig:
    """Embedder settings (ref: configuration.py:114-138)."""

    model_name: str = configfield("model_name", default="e5-base-tpu", help_txt="Embedding model name.")
    dimensions: int = configfield("dimensions", default=512, help_txt="Embedding dimensionality.")
    model_engine: str = configfield("model_engine", default="tpu", help_txt="tpu|openai-compat.")
    server_url: str = configfield("server_url", default="", help_txt="Remote embedding server; empty = in-process.")
    microbatch_window_ms: float = configfield("microbatch_window_ms", default=2.0, help_txt="Cross-request embed micro-batch wait window in ms; 0 disables coalescing (encoders/microbatch.py).")


@dataclass(frozen=True)
class RankingConfig:
    """Reranker settings (ref: configuration.py ranking section, utils.py:448-471)."""

    model_name: str = configfield("model_name", default="rerank-minilm-tpu", help_txt="Cross-encoder model name.")
    model_engine: str = configfield("model_engine", default="tpu", help_txt="tpu|openai-compat.")
    server_url: str = configfield("server_url", default="", help_txt="Remote rerank server; empty = in-process.")
    microbatch_window_ms: float = configfield("microbatch_window_ms", default=2.0, help_txt="Cross-request rerank micro-batch wait window in ms; 0 disables coalescing (encoders/microbatch.py).")


@dataclass(frozen=True)
class RetrieverConfig:
    """Retrieval knobs (ref: configuration.py:140-165)."""

    top_k: int = configfield("top_k", default=4, help_txt="Documents returned to the prompt (ref default 4).")
    score_threshold: float = configfield("score_threshold", default=0.25, help_txt="Minimum similarity score (ref default 0.25).")
    nr_top_k: int = configfield("nr_top_k", default=40, help_txt="Docs fetched before reranking (ref multi_turn 40→4 funnel).")
    max_context_tokens: int = configfield("max_context_tokens", default=1500, help_txt="Retrieved-context token budget (ref DEFAULT_MAX_CONTEXT, utils.py:103).")


@dataclass(frozen=True)
class EngineConfig:
    """In-tree TPU serving engine knobs (no reference equivalent — replaces NIM)."""

    role: str = configfield("role", default="unified", help_txt="Engine serving role for disaggregated prefill/decode topologies: unified (default — one worker does everything, today's zero-config behavior) | prefill (runs chunked prefill only and exports the finished request's KV pages + sampling state via /v1/kv/prefill; never dispatches decode) | decode (full worker that additionally imports handed-off KV via /v1/kv/handoff and decodes from the first token on). The failover router (server/failover.py) discovers roles from /health and routes phases to the matching workers.")
    max_batch_size: int = configfield("max_batch_size", default=8, help_txt="Decode-slot capacity of the continuous batcher.")
    max_seq_len: int = configfield("max_seq_len", default=2048, help_txt="KV-cache length per slot.")
    page_size: int = configfield("page_size", default=128, help_txt="KV page granularity (tokens).")
    num_pages: int = configfield("num_pages", default=0, help_txt="Physical KV pages in the pool (bounds HBM by live tokens); 0 = full slot capacity.")
    prefix_cache: str = configfield("prefix_cache", default="on", help_txt="Prefix caching over the paged KV pool: on | off. Hash-identified full prompt pages are shared across requests (refcounted, LRU-evicted under pool pressure), so repeated chat templates / system prompts / retrieved chunks skip re-prefill — the TRT-LLM prefix-reuse capability in-tree.")
    prefill_chunk: int = configfield("prefill_chunk", default=512, help_txt="Chunked-prefill bucket size.")
    decode_steps_per_dispatch: int = configfield("decode_steps_per_dispatch", default=8, help_txt="Decode steps fused into one device dispatch (lax.scan); amortizes host sync latency. Must be a power of two (each distinct step count is a separate compile).")
    decode_steps_max: int = configfield("decode_steps_max", default=0, help_txt="Adaptive upper bound on fused decode steps: when the batch is at least half full and every active slot has the budget, dispatches deepen up to this many steps (power of two; 0 = always use decode_steps_per_dispatch). Pays when dispatch round trips bound throughput; a device-bound engine is better off at the base depth (measured round 4).")
    decode_multistep: int = configfield("decode_multistep", default=0, help_txt="Multi-step decode scans with deferred token fetch: ceiling M of the multiplier ladder (power of two >= 2; 0 = off). Eligible steady-state dispatches (no grammar, no top-logprobs, no speculative widening pending, no imminent stop match) scan decode_steps_per_dispatch x M plain decode steps in ONE device program (decode_multi / s<K>m<M> ledger keys) and the scheduler fetches the accumulated token block once per dispatch — host fetches per generated token drop by up to M. The stop/EOS decision rides on-device: EOS/budget/capacity masking as in the per-step scan, plus a conservative stop-string maybe-match flag over a ring of recent token ids that pauses a slot until the host confirms, bounding overshoot. The M ladder is bounded like the width ladder and warmup pre-compiles every rung — M transitions never recompile mid-serving. Emitted streams stay token-identical to the per-step path (the host replays detokenization/stop holdback over the fetched block). The bare env APP_DECODE_MULTISTEP overrides this field.")
    pipeline_depth: int = configfield("pipeline_depth", default=2, help_txt="Decode dispatches kept in flight ahead of result processing. Deeper hides more host-device sync latency but delays done-slot detection by depth x fetch time, costing batch occupancy; 2 measured best on a remote-attached chip once grouped prefill removed the ramp bottleneck (round 4).")
    prefill_group: int = configfield("prefill_group", default=8, help_txt="Max prompts whose prefill chunks are batched into ONE dispatch (group sizes bucketed to powers of two; each bucket is a separate compile). Amortizes per-dispatch overhead during admission ramps and slot refills.")
    prefill_hold_chunks: int = configfield("prefill_hold_chunks", default=16, help_txt="While admissions are prefilling into a batch under half full, hold decode dispatches for up to this many prefill chunks per ramp episode (each decode dispatch at low fill burns a full host round trip on few tokens). 0 disables holding; decode always resumes once the budget is spent, bounding any streamer stall.")
    donate_buffers: str = configfield("donate_buffers", default="auto", help_txt="Donate the KV pool through dispatches: on | off | auto (off on remote-attached chips, where the client blocks ~RTT per donated dispatch; costs a transient 2x pool copy when off).")
    dtype: str = configfield("dtype", default="bfloat16", help_txt="Activation/weight dtype.")
    quant: str = configfield("quant", default="none", help_txt="Weight quantization: none | int8 (per-channel weight-only; halves weight HBM reads — the decode bottleneck — and fits 8B-class weights on one v5e chip).")
    kv_quant: str = configfield("kv_quant", default="none", help_txt="KV-cache quantization: none | int8 (per-token-per-head scales, dequant folded past the attention dots — TRT-LLM kv-cache-quant parity). Halves the pool's HBM footprint and measured +5% decode throughput on v5e (round 4).")
    spec_decode: str = configfield("spec_decode", default="on", help_txt="Prompt-lookup speculative decoding: on | off. Each decode step drafts spec_draft tokens from the request's own token history (n-gram continuation — RAG outputs quote their context) and verifies them in one widened step; decode is weight-read-bound, so accepted drafts are nearly free tokens. Output is token-identical to non-speculative decoding (exact-match acceptance under the per-request seed).")
    spec_draft: int = configfield("spec_draft", default=4, help_txt="Drafted tokens verified per decode step when spec_decode=on (the widened step processes 1+spec_draft positions per slot). With spec_adaptive=on this is the CEILING of the width ladder, not a fixed width.")
    spec_ngram: int = configfield("spec_ngram", default=2, help_txt="Suffix n-gram length matched against the request's history to locate a draft continuation.")
    spec_adaptive: str = configfield("spec_adaptive", default="on", help_txt="Acceptance-tuned speculative width: on (default) | off. Each slot's draft length is capped by a trailing acceptance EMA (fed by the spec_accept_len signal) and the dispatch compiles at the smallest pow2-ish width-ladder rung covering every slot's cap — warmup pre-compiles every rung, so width changes never recompile mid-serving. Output is token-identical to the static width by construction (exact-match acceptance under the per-request seed); only wasted/won verify positions change. off = every dispatch runs the full 1+spec_draft width (the pre-r06 behavior).")
    spec_draft_max: int = configfield("spec_draft_max", default=0, help_txt="Ceiling of the adaptive width ladder in drafted tokens; 0 = auto (2 x spec_draft when spec_adaptive=on, else spec_draft). High-acceptance slots (quoting RAG answers) climb past the configured spec_draft up to this ceiling — the r05 static draft was wrong in BOTH directions.")
    decode_width_ladder: str = configfield("decode_width_ladder", default="on", help_txt="Batch-width ladder for PURE-decode dispatches: on (default) | off. At low occupancy the decode program runs at the smallest pre-compiled width rung covering the highest live slot (slots are allocated lowest-id-first so the live set compacts), shrinking the padded (batch x spec_width) token block the ledger reports as engine_padding_waste_frac. Mixed-phase dispatches keep the full width (their padding is already filled by fused prefill chunks). Warmup pre-compiles every rung; ladder transitions never recompile mid-serving.")
    max_adapters: int = configfield("max_adapters", default=4, help_txt="Resident LoRA adapter slots for per-request multi-adapter serving (slot 0 is the base model). Requests select an adapter by registered name (OpenAI `model` field); one decode batch mixes adapters freely.")
    model_family: str = configfield("model_family", default="llama3-8b", help_txt="Served model architecture (models.model_configs name, same names as the train CLI); APP_LLM_MODEL_NAME stays the cosmetic OpenAI model id.")
    long_prefill: str = configfield("long_prefill", default="auto", help_txt="Sequence-parallel whole-prompt prefill for multi-chunk prompts: auto (when the mesh has a seq axis) | off. One ring-attention pass replaces the chunk loop; decode does not interleave during it, but the pass is seq-axis-times faster.")
    mixed_phase_dispatch: str = configfield("mixed_phase_dispatch", default="auto", help_txt="Mixed-phase dispatch (ragged paged attention): pack the oldest admission's prefill chunk INTO the decode dispatch so one program serves prefill-chunk + decode rows with per-row lengths — long prompts stop stalling the decode tick and the MXU sees fatter tiles. on | off | auto (on for single-chip TPU serving, off elsewhere); the bare env APP_MIXED_PHASE_DISPATCH overrides. 'on' fails loudly at engine init if the config cannot be served (TP mesh, sliding window, unsupported page/head shape).")
    devtime: str = configfield("devtime", default="off", help_txt="Device-time attribution ledger (observability/devtime.py): off (default — dispatch counts and compile-watch only, ZERO added device fences) | sample (one timing fence every APP_DEVTIME_SAMPLE_N-th dispatch — live engine_mfu/engine_hbm_read_util gauges at bounded overhead) | on (fence every dispatch — full attribution for bench/debug; serializes the dispatch pipeline, never the serving default). The bare env APP_DEVTIME overrides this field.")
    qos: str = configfield("qos", default="off", help_txt="QoS admission plane (engine/qos.py): off (default — the scheduler's FIFO-with-bounded-bypass admission, byte-identical to pre-QoS behavior, zero added work) | fair (per-tenant weighted fair queuing with virtual-time accounting, earliest-deadline-first within a tenant, APP_QOS_TOKENS_PER_S token-rate quotas, shed-before-prefill for unmeetable deadlines, slack-aware preemption, and cost-modeled router hedging). Tenant weights ride APP_QOS_TENANT_WEIGHTS ('acme=4,*=1'). The bare env APP_QOS overrides this field; docs/scheduling.md is the operator guide.")
    kv_spill_mb: int = configfield("kv_spill_mb", default=0, help_txt="Bounded pinned host-RAM pool (MiB) for spill-preemption of KV pages (engine/spill.py): under page exhaustion the victim slot's pages are demoted to this pool instead of freed, and promotion re-imports them on-device when pages free — preemption costs one transfer instead of a full re-prefill recompute (ROADMAP item 3's HBM→host tier, in-process). 0 (default) = off, preemption recomputes as before. The bare env APP_KV_SPILL_MB overrides this field.")
    kv_tier: str = configfield("kv_tier", default="off", help_txt="Prefix-addressed host KV tier over the spill pool (engine/kv_tier.py): off (default — the request-keyed spill pool, byte-identical to pre-tier behavior) | prefix (spilled page runs are re-keyed by their token-level page-chain hashes and RETAINED after release as a refcounted, value-priced cache; admission probes the tier for the longest cached prefix of every prompt and promotes it with a partial page import — zero prefill programs over the covered span, prefill only the tail; returning conversations and fleet-shared system prompts stop re-prefilling). Requires a spill budget (kv_spill_mb / APP_KV_SPILL_MB > 0). The bare env APP_KV_TIER overrides this field.")
    kv_tier_disk_mb: int = configfield("kv_tier_disk_mb", default=0, help_txt="Optional disk tier (MiB) below the host-RAM KV tier: retained prefix entries are written behind (async, never on the driver thread) as crc32-framed files (core/kv_wire.py format — corruption is a loud decode failure, never served KV), so a RAM eviction demotes instead of drops and a later promote reloads from disk. 0 (default) = off. APP_KV_TIER_DISK_DIR picks the directory; the bare env APP_KV_TIER_DISK_MB overrides this field.")
    attention: str = configfield("attention", default="auto", help_txt="Attention backend: auto (pallas on TPU, xla elsewhere) | pallas | xla.")
    mesh_shape: str = configfield("mesh_shape", default="", help_txt="Device mesh, e.g. '1x8'; empty = all devices on one tensor axis.")
    checkpoint_dir: str = configfield("checkpoint_dir", default="", help_txt="Orbax checkpoint to serve; empty = random init (test mode).")


@dataclass(frozen=True)
class SLOInteractiveConfig:
    """Budgets for the ``interactive`` SLO class (chat-facing traffic — the
    BASELINE.md <1 s TTFT north star lives here)."""

    ttft_s: float = configfield("ttft_s", default=1.0, help_txt="Time-to-first-token budget (s) for interactive requests.")
    tpot_s: float = configfield("tpot_s", default=0.25, help_txt="Time-per-output-token budget (s) — streaming cadence after the first token.")
    e2e_s: float = configfield("e2e_s", default=30.0, help_txt="End-to-end deadline (s) stamped at chain-server admission.")
    sheddable: bool = configfield("sheddable", default=False, help_txt="May the scheduler shed this class under critical SLO pressure?")


@dataclass(frozen=True)
class SLOBatchConfig:
    """Budgets for the ``batch`` SLO class (offline-ish bulk work: eval
    runs, SDG, ingestion summarization)."""

    ttft_s: float = configfield("ttft_s", default=10.0, help_txt="Time-to-first-token budget (s) for batch requests.")
    tpot_s: float = configfield("tpot_s", default=1.0, help_txt="Time-per-output-token budget (s) for batch requests.")
    e2e_s: float = configfield("e2e_s", default=300.0, help_txt="End-to-end deadline (s) for batch requests.")
    sheddable: bool = configfield("sheddable", default=False, help_txt="May the scheduler shed this class under critical SLO pressure?")


@dataclass(frozen=True)
class SLOBestEffortConfig:
    """Budgets for the ``best_effort`` SLO class: the load-shedding valve.
    Under critical error-budget burn the scheduler rejects these at
    admission so interactive traffic keeps its budgets."""

    ttft_s: float = configfield("ttft_s", default=30.0, help_txt="Time-to-first-token budget (s) for best-effort requests.")
    tpot_s: float = configfield("tpot_s", default=2.0, help_txt="Time-per-output-token budget (s) for best-effort requests.")
    e2e_s: float = configfield("e2e_s", default=600.0, help_txt="End-to-end deadline (s) for best-effort requests.")
    sheddable: bool = configfield("sheddable", default=True, help_txt="May the scheduler shed this class under critical SLO pressure?")


@dataclass(frozen=True)
class SLOConfig:
    """Serving objectives + burn-rate alerting (observability/slo.py).

    Attainment target and the Google-SRE-style paired burn-rate windows: a
    pressure level fires only when BOTH the fast and the slow window burn
    past a threshold — the fast window reacts to new incidents, the slow
    window keeps one latency blip from paging."""

    default_class: str = configfield("default_class", default="interactive", help_txt="SLO class assumed when a request carries no X-Request-Class.")
    target: float = configfield("target", default=0.99, help_txt="Attainment objective per class (0.99 = 1% error budget).")
    fast_window_s: float = configfield("fast_window_s", default=300.0, help_txt="Fast burn-rate window (s) — reacts to new incidents.")
    slow_window_s: float = configfield("slow_window_s", default=3600.0, help_txt="Slow burn-rate window (s) — confirms the incident is sustained.")
    warn_burn: float = configfield("warn_burn", default=2.0, help_txt="Burn-rate threshold (x error budget) both windows must exceed for pressure=warn.")
    critical_burn: float = configfield("critical_burn", default=10.0, help_txt="Burn-rate threshold both windows must exceed for pressure=critical (sheds best_effort).")
    min_events: int = configfield("min_events", default=10, help_txt="Minimum finished requests in the fast window before pressure can leave ok (no paging on 2 requests).")
    interactive: SLOInteractiveConfig = configfield("interactive", default_factory=SLOInteractiveConfig, help_txt="Interactive-class budgets.")
    batch: SLOBatchConfig = configfield("batch", default_factory=SLOBatchConfig, help_txt="Batch-class budgets.")
    best_effort: SLOBestEffortConfig = configfield("best_effort", default_factory=SLOBestEffortConfig, help_txt="Best-effort-class budgets.")


@dataclass(frozen=True)
class AppConfig:
    """Top-level app configuration (ref: configuration.py:166-204)."""

    vector_store: VectorStoreConfig = configfield("vector_store", default_factory=VectorStoreConfig, help_txt="Vector store.")
    llm: LLMConfig = configfield("llm", default_factory=LLMConfig, help_txt="LLM engine.")
    text_splitter: TextSplitterConfig = configfield("text_splitter", default_factory=TextSplitterConfig, help_txt="Splitter.")
    embeddings: EmbeddingConfig = configfield("embeddings", default_factory=EmbeddingConfig, help_txt="Embedder.")
    ranking: RankingConfig = configfield("ranking", default_factory=RankingConfig, help_txt="Reranker.")
    retriever: RetrieverConfig = configfield("retriever", default_factory=RetrieverConfig, help_txt="Retriever.")
    engine: EngineConfig = configfield("engine", default_factory=EngineConfig, help_txt="TPU engine.")
    slo: SLOConfig = configfield("slo", default_factory=SLOConfig, help_txt="Serving SLOs + burn-rate alerting.")


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

def load_config(path: Optional[str] = None, cls: type = AppConfig) -> Any:
    """Load config from YAML/JSON ``path`` (or ``APP_CONFIG_FILE``) + env overlay.

    A missing/empty path yields all-defaults, matching the reference's
    ``/dev/null`` default config file (ref: utils.py:180-186).
    """
    path = path or os.environ.get(f"{ENV_PREFIX}_CONFIG_FILE", "")
    data: Dict[str, Any] = {}
    if path and os.path.exists(path) and os.path.isfile(path):
        with open(path, "r", encoding="utf-8") as fh:
            text = fh.read()
        if text.strip():
            try:
                data = json.loads(text)
            except json.JSONDecodeError:
                data = yaml.safe_load(text) or {}
        if not isinstance(data, dict):
            logger.warning("config file %s did not parse to a mapping; using defaults", path)
            data = {}
    return _from_dict(cls, data, ENV_PREFIX)


@lru_cache(maxsize=1)
def get_config() -> AppConfig:
    """Cached process-wide config (ref: utils.py get_config lru_cache pattern, utils.py:137-186)."""
    return load_config()


def print_help(stream: Optional[TextIO] = None, cls: type = AppConfig) -> None:
    """Print every supported env var with type, default, and help text
    (ref: configuration_wizard.py:95-162 auto-generated help)."""
    import sys

    stream = stream or sys.stdout
    print(f"{ENV_PREFIX}_CONFIG_FILE  <str>  path to YAML/JSON config file", file=stream)
    for env_name, ftype, default, help_txt in _iter_env_vars(cls, ENV_PREFIX):
        tname = getattr(ftype, "__name__", str(ftype))
        print(f"{env_name}  <{tname}>  default={default!r}  {help_txt}", file=stream)
