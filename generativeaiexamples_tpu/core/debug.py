"""Numerics debug modes — the §5.2 story XLA leaves to us.

The reference has no sanitizers (SURVEY §5.2: no TSAN/ASAN, nothing —
XLA removes most data-race surface here, so what remains is *numerics*):

  * ``APP_DEBUG_NANS=1``  — jax's debug_nans: any NaN produced under jit
    raises at the producing op instead of surfacing 40 layers later as a
    garbage logit (the float analogue of a sanitizer trap);
  * ``APP_DEBUG_DETERMINISM=1`` — forces XLA's deterministic op lowering
    and pins the Python hash seed check, so a failing run replays bit-
    identically (deterministic-seed test paths per SURVEY §5.2).

`install()` is called by the serving/training entrypoints before any jax
computation; it is a no-op unless a mode is requested, costs nothing in
production, and logs what it armed so a slowdown is never a mystery
(debug_nans disables donation/async dispatch — dev-only by design).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_installed = False


def _flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes")


def install() -> None:
    """Arm the requested debug modes (idempotent; call before jax work)."""
    global _installed
    if _installed:
        return
    _installed = True
    if _flag("APP_DEBUG_NANS"):
        import jax

        jax.config.update("jax_debug_nans", True)
        logger.warning("APP_DEBUG_NANS armed: NaNs raise at the producing "
                       "op; dispatch is synchronous (dev mode)")
    if _flag("APP_DEBUG_DETERMINISM"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_gpu_deterministic_ops" not in flags:
            # harmless on TPU (ignored), load-bearing on GPU dev boxes
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_gpu_deterministic_ops=true").strip()
        if "PYTHONHASHSEED" not in os.environ:
            logger.warning("APP_DEBUG_DETERMINISM set but PYTHONHASHSEED "
                           "is not — dict iteration order may still vary "
                           "across restarts")
        logger.warning("APP_DEBUG_DETERMINISM armed: deterministic XLA "
                       "lowering requested")
