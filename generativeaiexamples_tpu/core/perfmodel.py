"""Analytic FLOP / HBM-byte models — the ONE copy bench.py and the live
device-time ledger (observability/devtime.py) both compute from.

Until PR 9 these formulas lived inline in bench.py, so the serving engine
could not report a live MFU and a future bench edit could silently skew
the recorded trajectory. Everything here is first-principles arithmetic
over public model facts:

  * decoder-only transformer FLOPs ≈ ``2 · n_params`` per processed token
    (the forward matmuls touch every weight once; attention-score FLOPs are
    a small correction at serving context lengths and are deliberately
    excluded — the same convention BASELINE.json's targets use);
  * decode is weight-read-bound: every fused decode step re-reads the full
    weight set, so weight-read HBM traffic is ``steps · param_bytes`` with
    ``param_bytes`` the quant-aware resident weight footprint;
  * chip peaks are the published bf16 matmul FLOP/s and HBM bandwidth per
    TPU generation (``CHIP_PEAKS``), keyed by ``device_kind`` substring.

A tier-1 test (tests/test_devtime.py) pins these outputs for one known
config against hand-derived constants AND against bench.py's reporting
helper, so an edit to either side fails loudly instead of drifting.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

# bf16 matmul peak (FLOP/s) and HBM bandwidth (B/s) per chip generation,
# keyed by a substring of jax's ``device_kind``
CHIP_PEAKS = {
    "v5 lite": (197e12, 819e9),    # v5e
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6": (918e12, 1640e9),        # Trillium
}


def chip_peaks(device) -> Tuple[Optional[float], Optional[float]]:
    """(peak_flops, peak_hbm_bw) for a jax device; (None, None) when the
    generation is unknown (CPU, simulators) — callers must treat utilization
    as unreportable then, never as zero."""
    kind = getattr(device, "device_kind", "") or ""
    for key, peaks in CHIP_PEAKS.items():
        if key in kind:
            return peaks
    return (None, None)


def decode_flops(n_params: int, tokens: float) -> float:
    """Model FLOPs to process ``tokens`` token positions (prefill or
    decode): 2 FLOPs per parameter per token."""
    return 2.0 * float(n_params) * float(tokens)


def weight_bytes(n_params: int, quant: str, dtype_itemsize: int) -> float:
    """Resident weight footprint in bytes — what one full weight read
    (one decode step) streams from HBM. int8 weight-only quantization
    stores 1 byte/param (per-channel scales are noise next to the weights
    and excluded, matching the bench's historical arithmetic)."""
    return float(n_params) * (1 if quant == "int8" else int(dtype_itemsize))


@dataclass(frozen=True)
class PerfModel:
    """One model-on-one-chip analytic envelope: FLOPs, weight bytes, peaks.

    ``mfu``/``hbm_read_util`` return None (not 0.0) when the chip's peaks
    are unknown — an unknown denominator must never masquerade as an idle
    chip."""

    n_params: int
    param_bytes: float
    peak_flops: Optional[float] = None
    peak_bw: Optional[float] = None

    @classmethod
    def build(cls, n_params: int, quant: str, dtype_itemsize: int,
              device=None) -> "PerfModel":
        peak_flops, peak_bw = chip_peaks(device) if device is not None \
            else (None, None)
        return cls(n_params=int(n_params),
                   param_bytes=weight_bytes(n_params, quant, dtype_itemsize),
                   peak_flops=peak_flops, peak_bw=peak_bw)

    def flops(self, tokens: float) -> float:
        return decode_flops(self.n_params, tokens)

    def weight_read_bytes(self, weight_passes: float) -> float:
        """HBM bytes streamed by ``weight_passes`` full weight reads (one
        per fused decode step; grouped prefill pays one per dispatch)."""
        return float(weight_passes) * self.param_bytes

    def prefill_seconds(self, tokens: float) -> Optional[float]:
        """Lower-bound device seconds to prefill ``tokens`` positions at
        the chip's matmul peak — the recompute cost a cached KV prefix
        saves, and therefore the value basis for the prefix tier's
        eviction pricing (engine/kv_tier.py). None when the chip's peaks
        are unknown — the tier falls back to a token-count proxy, never
        to pricing every entry at zero."""
        if not self.peak_flops:
            return None
        return self.flops(tokens) / self.peak_flops

    def mfu(self, tokens: float, seconds: float) -> Optional[float]:
        """Achieved model-FLOP utilization of ``tokens`` positions computed
        in ``seconds`` of device time."""
        if not self.peak_flops or seconds <= 0:
            return None
        return self.flops(tokens) / seconds / self.peak_flops

    def hbm_read_util(self, weight_passes: float,
                      seconds: float) -> Optional[float]:
        """Fraction of peak HBM bandwidth consumed by weight re-reads."""
        if not self.peak_bw or seconds <= 0:
            return None
        return self.weight_read_bytes(weight_passes) / seconds / self.peak_bw
