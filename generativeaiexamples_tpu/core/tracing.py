"""Tracing bootstrap + instrumentation decorators for server endpoints.

TPU-stack equivalent of RAG/src/chain_server/tracing.py: the reference sets up
an OTel provider then wraps endpoint coroutines so each request gets a span
with the incoming HTTP trace context attached
(ref: tracing.py:36-59 provider setup; 62-103 wrapper decorators).

Here the wrappers target aiohttp handlers (our chain server) and arbitrary
chain methods; span context rides the in-tree tracer
(generativeaiexamples_tpu.observability.otel).
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable

from generativeaiexamples_tpu.observability import otel

# honor APP_TRACING_EXPORTER at process start (console | jsonl | otlp |
# memory); a no-op when unset — the reference's compose files likewise pick
# the exporter via OTEL_EXPORTER_OTLP_ENDPOINT env wiring
otel.configure_from_env()

tracer = otel.get_tracer("generativeaiexamples_tpu")


def instrumentation_wrapper(func: Callable) -> Callable:
    """Wrap an aiohttp handler: extract remote traceparent, open a span named
    after the handler (ref: tracing.py:103-114 instrumentation_wrapper)."""

    @functools.wraps(func)
    async def wrapper(request: Any, *args: Any, **kwargs: Any) -> Any:
        headers = dict(getattr(request, "headers", {}) or {})
        parent = otel.extract_traceparent(headers)
        with otel.use_parent(parent):
            with tracer.span(f"http:{func.__name__}",
                             attributes={"http.path": str(getattr(request, "path", ""))}):
                return await func(request, *args, **kwargs)

    return wrapper


def chain_instrumentation(func: Callable) -> Callable:
    """Wrap a chain method (llm_chain / rag_chain / ingest_docs) in a span
    (ref: langchain_instrumentation_class_wrapper, tracing.py:87-93)."""

    if inspect.isasyncgenfunction(func):
        @functools.wraps(func)
        async def agen_wrapper(*args: Any, **kwargs: Any) -> Any:
            with tracer.span(f"chain:{func.__qualname__}") as span:
                n = 0
                async for item in func(*args, **kwargs):
                    n += 1
                    yield item
                span.set_attribute("chunks", n)
        return agen_wrapper

    if inspect.isgeneratorfunction(func):
        @functools.wraps(func)
        def gen_wrapper(*args: Any, **kwargs: Any) -> Any:
            with tracer.span(f"chain:{func.__qualname__}") as span:
                n = 0
                for item in func(*args, **kwargs):
                    n += 1
                    yield item
                span.set_attribute("chunks", n)
        return gen_wrapper

    @functools.wraps(func)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        with tracer.span(f"chain:{func.__qualname__}"):
            return func(*args, **kwargs)

    return wrapper
