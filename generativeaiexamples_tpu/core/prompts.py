"""Prompt templates with user-override merge.

Parity with the reference's prompt config: each example ships default
templates; a user-mounted YAML overrides/extends them
(ref: per-example prompt.yaml; merge logic get_prompts/_combine_dicts,
utils.py:190-216, 689-715; mount point docker-compose.yaml:17-18).
Override file path comes from ``APP_PROMPTS_FILE``.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Any, Dict, Optional

import yaml

DEFAULT_PROMPTS: Dict[str, str] = {
    # ref basic_rag prompt.yaml semantics: a chat template and a rag template
    "chat_template": (
        "You are a helpful, respectful and honest assistant. Always answer as "
        "helpfully as possible. If you don't know the answer to a question, "
        "say so rather than guessing."),
    "rag_template": (
        "You are a helpful AI assistant. Use the following pieces of retrieved "
        "context to answer the question. If the context does not contain the "
        "answer, say you don't know. Keep the answer concise.\n\n"
        "Context:\n{context}\n"),
    "multi_turn_rag_template": (
        "You are a document chatbot. Answer the user's question using only the "
        "retrieved context and the conversation so far. If unsure, say so. "
        "Make your response conversational.\n\n"
        "Conversation history retrieved:\n{history}\n\n"
        "Document context retrieved:\n{context}\n"),
    "query_rewriter_prompt": (
        "Given the conversation history and a follow-up question, rewrite the "
        "follow-up into a standalone question. Return only the question."),
    # query-decomposition agent (ref: query_decomposition_rag/prompt.yaml
    # tool_selector_prompt / math_tool_prompt — JSON tool-request protocol)
    "tool_selector_prompt": (
        "Your task is to answer questions. If you cannot answer the question "
        "directly, request a tool and break the question into specific "
        "sub-questions. Fill with Nil where no action is required. Return ONLY "
        "a JSON object with the tool and the generated sub-questions — no "
        "other text. You are given two tools:\n"
        "- Search: finds and retrieves relevant answers from the ingested "
        "documents.\n"
        "- Math: performs arithmetic (addition, subtraction, multiplication, "
        "division, comparisons).\n"
        "Do not pass sub-questions to a tool if the contextual information "
        "already answers them. If you have all the information needed, set "
        "Tool_Request to Nil.\n\n"
        "Contextual Information:\n{context}\n\n"
        "Question:\n{question}\n\n"
        '{{"Tool_Request": "<Fill>", "Generated Sub Questions": [<Fill>]}}'),
    "math_tool_prompt": (
        "Identify two numeric variables and one operation from the question. "
        "Return ONLY a JSON object with keys IsPossible (\"Possible\" or "
        "\"Not Possible\"), variable1, variable2, and operation (one of "
        "+ - * / = > < >= <=) — no other text.\n\n"
        "Contextual Information:\n{context}\n\n"
        "Question:\n{question}\n\n"
        '{{"IsPossible": "<Fill>", "variable1": <Fill>, "variable2": <Fill>, '
        '"operation": "<Fill>"}}'),
    "answer_extraction_prompt": (
        "Below is a question and a set of passages that may or may not be "
        "relevant. Extract the answer to the question using only the "
        "information in the passages. Be as concise as possible and only "
        "include the answer if present. Do not infer beyond the passages."),
    # structured-data CSV chain (ref: structured_data_rag/prompt.yaml
    # csv_data_retrieval_template / csv_response_template)
    "csv_data_retrieval_template": (
        "You are an expert data analyst who writes pandas code.\n"
        "Write python code that computes the answer to the user's query from "
        "the DataFrame `df` (already loaded; do NOT read any files). Assign "
        "the final answer to a variable named `result`. Use only `df`, `pd`, "
        "and builtins. Return ONLY the code, no explanations or markdown.\n\n"
        "The data contains: {description}\n"
        "Instructions:\n{instructions}\n\n"
        "DataFrame columns and sample rows:\n{data_frame}\n"),
    "csv_response_template": (
        "Provide a response to the user's query based on the given data "
        "point. Do not add anything beyond the information provided in the "
        "data.\n\nUser's query:\n{query}\n\n"
        "Data point computed from the table:\n{data}\n\nResponse:"),
    "multimodal_rag_template": (
        "Answer using the retrieved text passages, table contents, and image "
        "descriptions.\n\nContext:\n{context}\n"),
    # agentic self-corrective RAG (ref: RAG/notebooks/langchain/
    # agentic_rag_with_nemo_retriever_nim.ipynb — grader/rewriter prompts)
    "retrieval_grader_prompt": (
        "You are a grader assessing the relevance of a retrieved document to "
        "a user question. If the document contains keywords or semantic "
        "meaning related to the question, grade it relevant. Return ONLY a "
        "JSON object {{\"score\": \"yes\"}} or {{\"score\": \"no\"}}.\n\n"
        "Document:\n{document}\n\nQuestion: {question}"),
    "hallucination_grader_prompt": (
        "You are a grader assessing whether an answer is grounded in the "
        "provided facts. Return ONLY a JSON object {{\"score\": \"yes\"}} if "
        "the answer is supported by the facts, else {{\"score\": \"no\"}}.\n\n"
        "Facts:\n{documents}\n\nAnswer: {generation}"),
    "answer_grader_prompt": (
        "You are a grader assessing whether an answer resolves the question. "
        "Return ONLY a JSON object {{\"score\": \"yes\"}} or "
        "{{\"score\": \"no\"}}.\n\nAnswer:\n{generation}\n\n"
        "Question: {question}"),
    "question_rewriter_prompt": (
        "You are a question re-writer that converts an input question into a "
        "better version optimized for vector-store retrieval. Reason about "
        "the underlying semantic intent. Return only the rewritten "
        "question.\n\nQuestion: {question}"),
}


def _combine(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for key, value in override.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _combine(out[key], value)
        else:
            out[key] = value
    return out


@lru_cache(maxsize=1)
def get_prompts(override_path: Optional[str] = None) -> Dict[str, Any]:
    prompts: Dict[str, Any] = dict(DEFAULT_PROMPTS)
    path = override_path or os.environ.get("APP_PROMPTS_FILE", "")
    if path and os.path.isfile(path):
        with open(path, "r", encoding="utf-8") as fh:
            user = yaml.safe_load(fh) or {}
        if isinstance(user, dict):
            prompts = _combine(prompts, user)
    return prompts
