"""Prompt templates with user-override merge.

Parity with the reference's prompt config: each example ships default
templates; a user-mounted YAML overrides/extends them
(ref: per-example prompt.yaml; merge logic get_prompts/_combine_dicts,
utils.py:190-216, 689-715; mount point docker-compose.yaml:17-18).
Override file path comes from ``APP_PROMPTS_FILE``.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Any, Dict, Optional

import yaml

DEFAULT_PROMPTS: Dict[str, str] = {
    # ref basic_rag prompt.yaml semantics: a chat template and a rag template
    "chat_template": (
        "You are a helpful, respectful and honest assistant. Always answer as "
        "helpfully as possible. If you don't know the answer to a question, "
        "say so rather than guessing."),
    "rag_template": (
        "You are a helpful AI assistant. Use the following pieces of retrieved "
        "context to answer the question. If the context does not contain the "
        "answer, say you don't know. Keep the answer concise.\n\n"
        "Context:\n{context}\n"),
    "multi_turn_rag_template": (
        "You are a document chatbot. Answer the user's question using only the "
        "retrieved context and the conversation so far. If unsure, say so.\n\n"
        "Context:\n{context}\n"),
    "query_rewriter_prompt": (
        "Given the conversation history and a follow-up question, rewrite the "
        "follow-up into a standalone question. Return only the question."),
    "tool_selector_prompt": (
        "Answer the question by decomposing it into simpler sub-questions when "
        "needed. Respond with a JSON list of sub-questions, or \"Nil\" if the "
        "question needs no decomposition."),
    "csv_prompt": (
        "You are a data analyst. Given the table description below, answer the "
        "user's question about the data.\n\nTable info:\n{table_info}\n"),
    "multimodal_rag_template": (
        "Answer using the retrieved text and image descriptions.\n\n"
        "Context:\n{context}\n"),
}


def _combine(base: Dict[str, Any], override: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for key, value in override.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _combine(out[key], value)
        else:
            out[key] = value
    return out


@lru_cache(maxsize=1)
def get_prompts(override_path: Optional[str] = None) -> Dict[str, Any]:
    prompts: Dict[str, Any] = dict(DEFAULT_PROMPTS)
    path = override_path or os.environ.get("APP_PROMPTS_FILE", "")
    if path and os.path.isfile(path):
        with open(path, "r", encoding="utf-8") as fh:
            user = yaml.safe_load(fh) or {}
        if isinstance(user, dict):
            prompts = _combine(prompts, user)
    return prompts
