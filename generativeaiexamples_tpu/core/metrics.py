"""Serving metrics registry: req/s, TTFT, tokens/s/chip, batch occupancy.

The reference has no first-class metrics (metrics ride on spans; SURVEY §5.5) —
these are the north-star measurements in BASELINE.json, so the TPU stack makes
them first-class: lock-protected counters + streaming histograms with exact
percentiles over a bounded reservoir, exposed via ``snapshot()`` and the chain
server's ``/metrics`` endpoint.
"""

from __future__ import annotations

import threading
import time
from bisect import insort
from typing import Dict, List, Optional


class Counter:
    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Bounded-reservoir histogram with exact percentiles (keeps newest N)."""

    def __init__(self, name: str, max_samples: int = 4096) -> None:
        self.name = name
        self._max = max_samples
        self._samples: List[float] = []
        self._ring: List[float] = []
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._count += 1
            self._sum += value
            self._ring.append(value)
            insort(self._samples, value)
            if len(self._ring) > self._max:
                old = self._ring.pop(0)
                idx = self._index(old)
                if idx is not None:
                    self._samples.pop(idx)

    def _index(self, value: float) -> Optional[int]:
        import bisect
        i = bisect.bisect_left(self._samples, value)
        if i < len(self._samples) and self._samples[i] == value:
            return i
        return None

    def percentile(self, q: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            idx = min(len(self._samples) - 1, int(q / 100.0 * len(self._samples)))
            return self._samples[idx]

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        """Total of all observed values (never evicted, unlike the
        percentile reservoir) — lets callers window a mean over an interval
        by differencing (sum, count) snapshots; bench.py windows the
        encoder micro-batch wait stats to the measured RAG phase this way."""
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0


class MetricsRegistry:
    def __init__(self) -> None:
        self._counters: Dict[str, Counter] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self._start = time.time()

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name)
            return self._histograms[name]

    def snapshot(self) -> Dict[str, object]:
        uptime = time.time() - self._start
        with self._lock:
            counters = dict(self._counters)
            histograms = dict(self._histograms)
        out: Dict[str, object] = {"uptime_s": round(uptime, 3)}
        for name, c in counters.items():
            out[name] = c.value
            out[f"{name}_per_s"] = round(c.value / uptime, 4) if uptime > 0 else 0.0
        for name, h in histograms.items():
            out[name] = {
                "count": h.count,
                "sum": round(h.sum, 6),
                "mean": round(h.mean, 6),
                "p50": round(h.percentile(50), 6),
                "p90": round(h.percentile(90), 6),
                "p99": round(h.percentile(99), 6),
            }
        return out


REGISTRY = MetricsRegistry()
