"""Serving metrics registry: req/s, TTFT, tokens/s/chip, batch occupancy.

The reference has no first-class metrics (metrics ride on spans; SURVEY §5.5) —
these are the north-star measurements in BASELINE.json, so the TPU stack makes
them first-class:

  * lock-protected ``Counter`` (monotonic), ``Gauge`` (last-value set/inc/dec),
    and streaming ``Histogram`` with exact percentiles over a bounded
    reservoir;
  * **labeled families**: ``REGISTRY.counter("requests_finished",
    labels={"finish": "eos"})`` keys a distinct time series per label set,
    rendered as ``requests_finished{finish="eos"}`` on both surfaces;
  * two exposition formats from one registry: ``snapshot()`` (the JSON
    ``/metrics`` blob) and ``render_prometheus()`` (text exposition format
    0.0.4 — scrapeable by a stock Prometheus without a sidecar exporter;
    histograms export ``_count``/``_sum`` plus quantile gauges, summary-style);
  * **windowed rates**: ``snapshot()`` reports each counter's
    ``<name>_rate_per_s`` over the window since the previous snapshot (the
    scrape interval), alongside the lifetime ``<name>_per_s`` average —
    lifetime rates go stale minutes into serving, the windowed rate is the
    current throughput a dashboard actually wants.
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Mapping, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Mapping[str, str]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label(value: str) -> str:
    return value.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _render_name(name: str, key: LabelKey) -> str:
    """Canonical series name: ``name`` or ``name{k="v",...}`` (the same
    rendering serves as the JSON snapshot key and the Prometheus line)."""
    if not key:
        return name
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return f"{name}{{{inner}}}"


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    return name if not name[:1].isdigit() else "_" + name


class Counter:
    """Monotonic counter (one labeled series of a family)."""

    def __init__(self, name: str, labels: Optional[Mapping[str, str]] = None
                 ) -> None:
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-value metric: queue depths, pool fill, batch occupancy *now*
    (counters answer "how many ever", gauges answer "how many right now" —
    the flight recorder mirrors its per-step engine state into these)."""

    def __init__(self, name: str, labels: Optional[Mapping[str, str]] = None
                 ) -> None:
        self.name = name
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Bounded-reservoir histogram with exact percentiles (keeps newest N).

    ``observe`` sits on the decode hot path (``decode_batch_fill``,
    ``fetch_rtt_s`` fire per dispatch), so it is O(1): a deque append +
    popleft — the old list reservoir paid ``pop(0)`` (shift every sample)
    plus a sorted-list ``insort`` + eviction (two more O(n) memmoves) on
    EVERY observe past capacity. The sorted view is built lazily at
    ``percentile()`` time instead (one O(n log n) sort amortized over every
    quantile of a scrape — reads are scrape-rate, writes are token-rate).
    """

    def __init__(self, name: str, max_samples: int = 4096,
                 labels: Optional[Mapping[str, str]] = None) -> None:
        self.name = name
        self.labels = dict(labels or {})
        self._max = max_samples
        self._ring: Deque[float] = deque(maxlen=max_samples)
        self._sorted: List[float] = []
        self._dirty = False
        self._count = 0
        self._sum = 0.0
        self._exemplar: Optional[Tuple[Dict[str, str], float, float]] = None
        self._lock = threading.Lock()

    def observe(self, value: float,
                exemplar: Optional[Mapping[str, str]] = None) -> None:
        with self._lock:
            self._count += 1
            self._sum += value
            self._ring.append(value)      # maxlen evicts the oldest
            self._dirty = True
            if exemplar:
                # newest exemplar wins: the point is "show me A trace for
                # this series", and recency beats any sampling scheme for
                # an incident drill-down (wall ts: exemplar timestamps are
                # reported instants, not interval math)
                self._exemplar = (dict(exemplar), float(value), time.time())

    @property
    def exemplar(self) -> Optional[Tuple[Dict[str, str], float, float]]:
        """(labels, value, unix_ts) of the newest exemplar-carrying
        observation (e.g. ``{"trace_id": ...}`` on the SLO path)."""
        with self._lock:
            return self._exemplar

    def percentile(self, q: float) -> float:
        with self._lock:
            if self._dirty:
                self._sorted = sorted(self._ring)
                self._dirty = False
            if not self._sorted:
                return 0.0
            idx = min(len(self._sorted) - 1, int(q / 100.0 * len(self._sorted)))
            return self._sorted[idx]

    def tail(self, n: int) -> List[float]:
        """The newest ``n`` observations, oldest first (bounded by the
        reservoir). Lets a bench window per-phase percentiles out of one
        histogram by differencing counts — the A/B consumer the KV-wire
        round uses; the exposition surfaces stay sum/count/percentile."""
        if n <= 0:
            return []
        with self._lock:
            return list(self._ring)[-n:]

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        """Total of all observed values (never evicted, unlike the
        percentile reservoir) — lets callers window a mean over an interval
        by differencing (sum, count) snapshots; bench.py windows the
        encoder micro-batch wait stats to the measured RAG phase this way."""
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0


class MetricsRegistry:
    def __init__(self) -> None:
        self._counters: Dict[Tuple[str, LabelKey], Counter] = {}
        self._gauges: Dict[Tuple[str, LabelKey], Gauge] = {}
        self._histograms: Dict[Tuple[str, LabelKey], Histogram] = {}
        self._lock = threading.Lock()
        # monotonic: uptime and rate windows are DURATIONS — an NTP step
        # on the wall clock must never yield a negative scrape window
        self._start = time.monotonic()
        # previous-snapshot counter values: the delta window for _rate_per_s
        self._rate_prev: Dict[str, float] = {}
        self._rate_t: float = self._start

    def counter(self, name: str,
                labels: Optional[Mapping[str, str]] = None) -> Counter:
        key = (name, _label_key(labels))
        with self._lock:
            if key not in self._counters:
                self._counters[key] = Counter(name, labels)
            return self._counters[key]

    def gauge(self, name: str,
              labels: Optional[Mapping[str, str]] = None) -> Gauge:
        key = (name, _label_key(labels))
        with self._lock:
            if key not in self._gauges:
                self._gauges[key] = Gauge(name, labels)
            return self._gauges[key]

    def histogram(self, name: str,
                  labels: Optional[Mapping[str, str]] = None) -> Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            if key not in self._histograms:
                self._histograms[key] = Histogram(name, labels=labels)
            return self._histograms[key]

    def family(self, name: str) -> Dict[LabelKey, float]:
        """Every live series of one counter/gauge family, keyed by its
        label set — the read surface label-bounded aggregations (the
        usage plane's per-worker MFU card, the cardinality-cap tests)
        use instead of groping through a full snapshot()."""
        with self._lock:
            out: Dict[LabelKey, float] = {
                lk: c.value for (n, lk), c in self._counters.items()
                if n == name}
            out.update({lk: g.value for (n, lk), g in self._gauges.items()
                        if n == name})
        return out

    def snapshot(self) -> Dict[str, object]:
        """JSON metrics blob. Counters carry both a lifetime ``_per_s`` and
        a ``_rate_per_s`` windowed over the interval since the previous
        snapshot — with a periodic scraper that window IS the scrape
        interval, so the rate tracks *current* throughput. Concurrent
        scrapers share the window state (each scrape resets it); point one
        collector at a process, not five.
        """
        now = time.monotonic()
        uptime = now - self._start
        with self._lock:
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
            window = now - self._rate_t
            prev = self._rate_prev
            # capture values and swap the window state under ONE lock hold:
            # a concurrent scrape then deltas against THIS capture over its
            # own (short) window — never lifetime totals over microseconds
            values = {(name, lk): c.value
                      for (name, lk), c in self._counters.items()}
            self._rate_prev = {_render_name(name, lk): v
                               for (name, lk), v in values.items()}
            self._rate_t = now
        out: Dict[str, object] = {"uptime_s": round(uptime, 3),
                                  "rate_window_s": round(window, 3)}
        for (name, lk), value in values.items():
            key = _render_name(name, lk)
            out[key] = value
            out[f"{key}_per_s"] = round(value / uptime, 4) if uptime > 0 else 0.0
            delta = value - prev.get(key, 0.0)
            out[f"{key}_rate_per_s"] = (round(delta / window, 4)
                                        if window > 1e-9 else 0.0)
        for (name, lk), g in gauges.items():
            out[_render_name(name, lk)] = g.value
        for (name, lk), h in histograms.items():
            out[_render_name(name, lk)] = {
                "count": h.count,
                "sum": round(h.sum, 6),
                "mean": round(h.mean, 6),
                "p50": round(h.percentile(50), 6),
                "p90": round(h.percentile(90), 6),
                "p99": round(h.percentile(99), 6),
            }
        return out

    def render_prometheus(self, openmetrics: bool = False) -> str:
        """Prometheus text exposition format 0.0.4 — or, with
        ``openmetrics=True``, OpenMetrics 1.0 (``# EOF`` terminator and
        **exemplars**: a histogram observed with ``exemplar={"trace_id":
        ...}`` renders ``… # {trace_id="…"} <value> <ts>`` on its
        ``_count`` sample, so a latency series links to a concrete trace).
        Exemplars are OpenMetrics-only: format 0.0.4 parsers reject the
        ``#`` suffix, and existing scrapers keep byte-stable output.

        Counters/gauges render as single samples per labeled series;
        histograms render summary-style — ``name{quantile="0.5"}`` exact
        reservoir quantiles plus the cumulative ``name_count``/``name_sum``
        (what ``rate(name_sum[1m]) / rate(name_count[1m])`` dashboards
        consume).
        """
        lines: List[str] = []
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            histograms = sorted(self._histograms.items())
            start = self._start
        lines.append("# TYPE process_uptime_seconds gauge")
        lines.append(f"process_uptime_seconds {time.monotonic() - start:.3f}")
        typed: set = set()
        for (name, lk), c in counters:
            pname = _sanitize(name)
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} counter")
            lines.append(f"{_render_name(pname, lk)} {c.value}")
        for (name, lk), g in gauges:
            pname = _sanitize(name)
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{_render_name(pname, lk)} {g.value}")
        for (name, lk), h in histograms:
            pname = _sanitize(name)
            if pname not in typed:
                typed.add(pname)
                lines.append(f"# TYPE {pname} summary")
            for q in (0.5, 0.9, 0.99):
                qkey = lk + (("quantile", f"{q}"),)
                lines.append(
                    f"{_render_name(pname, qkey)} {h.percentile(q * 100)}")
            count_line = f"{_render_name(pname + '_count', lk)} {h.count}"
            if openmetrics:
                ex = h.exemplar
                if ex is not None:
                    ex_labels, ex_value, ex_ts = ex
                    inner = ",".join(f'{k}="{_escape_label(str(v))}"'
                                     for k, v in sorted(ex_labels.items()))
                    count_line += (f" # {{{inner}}} {ex_value} "
                                   f"{ex_ts:.3f}")
            lines.append(count_line)
            lines.append(f"{_render_name(pname + '_sum', lk)} {h.sum}")
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()
