"""Token-aware text splitter.

Parity with the reference's splitter contract: chunks of `chunk_size` tokens
with `chunk_overlap` overlap, counted by a real tokenizer
(ref: SentenceTransformersTokenTextSplitter factory utils.py:474-489;
defaults 510/200, configuration.py:86-91). Splitting prefers paragraph, then
sentence, then whitespace boundaries before falling back to hard token cuts.
"""

from __future__ import annotations

import re
from typing import List, Optional

from generativeaiexamples_tpu.engine.tokenizer import Tokenizer, get_tokenizer

_PARAGRAPH = re.compile(r"\n\s*\n")
_SENTENCE = re.compile(r"(?<=[.!?])\s+")


class TokenTextSplitter:
    def __init__(self, chunk_size: int = 510, chunk_overlap: int = 200,
                 tokenizer: Optional[Tokenizer] = None) -> None:
        if chunk_overlap >= chunk_size:
            raise ValueError("chunk_overlap must be < chunk_size")
        self.chunk_size = chunk_size
        self.chunk_overlap = chunk_overlap
        self.tokenizer = tokenizer or get_tokenizer("")

    def _count(self, text: str) -> int:
        return len(self.tokenizer.encode(text))

    def split(self, text: str) -> List[str]:
        if not text.strip():
            return []
        if self._count(text) <= self.chunk_size:
            return [text.strip()]

        pieces = self._atomize(text)
        chunks: List[str] = []
        current: List[str] = []
        current_tokens = 0
        for piece, n in pieces:
            if current and current_tokens + n > self.chunk_size:
                chunks.append(" ".join(current).strip())
                # carry back overlap worth of trailing pieces
                keep: List[str] = []
                kept = 0
                for prev in reversed(current):
                    pn = self._count(prev)
                    if kept + pn > self.chunk_overlap:
                        break
                    keep.insert(0, prev)
                    kept += pn
                current = keep
                current_tokens = kept
            current.append(piece)
            current_tokens += n
        if current:
            chunks.append(" ".join(current).strip())
        return [c for c in chunks if c]

    def _atomize(self, text: str):
        """Break into (piece, token_count) units each ≤ chunk_size."""
        out = []
        for para in _PARAGRAPH.split(text):
            if not para.strip():
                continue
            if self._count(para) <= self.chunk_size:
                out.append((para.strip(), self._count(para)))
                continue
            for sent in _SENTENCE.split(para):
                n = self._count(sent)
                if n <= self.chunk_size:
                    if sent.strip():
                        out.append((sent.strip(), n))
                    continue
                # hard cut by tokens
                ids = self.tokenizer.encode(sent)
                for i in range(0, len(ids), self.chunk_size):
                    part = self.tokenizer.decode(ids[i:i + self.chunk_size])
                    if part.strip():
                        out.append((part.strip(), self._count(part)))
        return out
