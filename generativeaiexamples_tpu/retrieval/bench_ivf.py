"""IVF vs exact search microbench at million-vector scale.

    python -m generativeaiexamples_tpu.retrieval.bench_ivf [--n 1000000]

The parity target is Milvus ``GPU_IVF_FLAT`` (ref: RAG/examples/local_deploy/
docker-compose-vectordb.yaml:55-85, chain_server/configuration.py:42-44): a
probe-bounded index whose per-query work does not grow with N. This prints
per-query latency for the exact GEMM path and the IVF gather path over the
same synthetic corpus, plus recall@10 of IVF against the exact ranking —
the proof that the gather does less work, not recall-parity cosmetics.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from generativeaiexamples_tpu.retrieval.store import Document, VectorStore


def _timed_queries(store: VectorStore, queries: np.ndarray, top_k: int):
    results = []
    store.search(queries[0], top_k=top_k)          # compile
    t0 = time.perf_counter()
    for q in queries:
        results.append(store.search(q, top_k=top_k))
    wall = time.perf_counter() - t0
    return wall / len(queries), results


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--dim", type=int, default=384)     # e5-small class
    ap.add_argument("--nlist", type=int, default=1024)
    ap.add_argument("--nprobe", type=int, default=32)
    ap.add_argument("--queries", type=int, default=32)
    ap.add_argument("--top-k", type=int, default=10)
    args = ap.parse_args()

    rng = np.random.default_rng(0)
    # clustered corpus (mixture of gaussians): IVF's intended regime, and
    # what real embedding spaces look like
    n_modes = max(args.nlist // 2, 1)
    modes = rng.standard_normal((n_modes, args.dim)).astype(np.float32)
    which = rng.integers(0, n_modes, args.n)
    emb = modes[which] + 0.15 * rng.standard_normal(
        (args.n, args.dim)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    docs = [Document(content=str(i)) for i in range(args.n)]

    exact = VectorStore(dim=args.dim, index_type="exact")
    ivf = VectorStore(dim=args.dim, index_type="ivf",
                      nlist=args.nlist, nprobe=args.nprobe)
    t0 = time.perf_counter()
    chunk = 100_000
    for s in range(0, args.n, chunk):
        exact.add(docs[s:s + chunk], emb[s:s + chunk])
        ivf.add(docs[s:s + chunk], emb[s:s + chunk])
    ingest_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    ivf.search(emb[0], top_k=args.top_k)      # build (train + group) once
    build_s = time.perf_counter() - t0

    q_ix = rng.integers(0, args.n, args.queries)
    queries = emb[q_ix] + 0.05 * rng.standard_normal(
        (args.queries, args.dim)).astype(np.float32)

    exact_s, exact_res = _timed_queries(exact, queries, args.top_k)
    ivf_s, ivf_res = _timed_queries(ivf, queries, args.top_k)

    recalls = []
    for e_hits, i_hits in zip(exact_res, ivf_res):
        truth = {d.content for d, _ in e_hits}
        got = {d.content for d, _ in i_hits}
        recalls.append(len(truth & got) / max(len(truth), 1))

    cell_cap = ivf._grouped.shape[1]
    print(json.dumps({
        "n": args.n, "dim": args.dim,
        "nlist": args.nlist, "nprobe": args.nprobe,
        "exact_ms_per_query": round(exact_s * 1e3, 3),
        "ivf_ms_per_query": round(ivf_s * 1e3, 3),
        "speedup": round(exact_s / ivf_s, 2),
        "recall_at_10_vs_exact": round(float(np.mean(recalls)), 4),
        "rows_scanned_ivf": args.nprobe * cell_cap,
        "rows_scanned_exact": args.n,
        "build_s": round(build_s, 2),
        "ingest_s": round(ingest_s, 2),
    }))


if __name__ == "__main__":
    main()
