"""Streaming ingest pipeline — staged async sources → chunk → embed → store.

Behavioral parity with the reference's Morpheus-based streaming VDB upload
(ref: community/streaming_ingest_rag/morpheus_examples/streaming_ingest_rag/
vdb_upload/module/ — file_source_pipe / rss_source_pipe / kafka_source_pipe
feed content_extractor_module → raw_chunker_module → schema_transform →
vdb_resource_tagging_module → embeddings → VDB upload; runner
vdb_upload/{run,pipeline}.py). Morpheus's GPU pipeline-parallel engine is
replaced by an asyncio staged pipeline: stages are coroutines joined by
bounded queues (backpressure, pipeline parallelism), and the embed stage
batches chunks so the TPU sees large device batches instead of per-doc
calls — the part of Morpheus's job that actually matters here.

Sources are pluggable async iterators yielding `SourceItem(content, source,
collection)`; file and JSONL sources are in-tree, Kafka/RSS arrive by
writing a ~10-line async generator against the same contract (the
reference's scale-out story — more workers — becomes more source tasks).
"""

from __future__ import annotations

import asyncio
import dataclasses
import glob as globlib
import json
import logging
import time
from typing import AsyncIterator, Callable, Dict, List, Optional, Sequence

from generativeaiexamples_tpu.retrieval.store import Document

logger = logging.getLogger(__name__)

_STOP = object()


@dataclasses.dataclass
class SourceItem:
    """One unit of raw content entering the pipeline. Sources report their
    per-item failures as data (``error`` set, empty content) so the central
    stats see every dropped document — a source generator has no other
    channel to the ingestor's accounting."""
    content: str
    source: str                      # provenance label (filename, url, topic)
    collection: str = "default"      # resource tag (vdb_resource_tagging)
    error: str = ""                  # non-empty = failed item (counted, skipped)


@dataclasses.dataclass
class IngestStats:
    items: int = 0
    chunks: int = 0
    embedded: int = 0
    stored: int = 0
    errors: int = 0
    wall_s: float = 0.0


async def file_source(paths: Sequence[str],
                      collection: str = "default") -> AsyncIterator[SourceItem]:
    """Glob-expanding file source (ref file_source_pipe.py); parsing runs in
    a thread so a slow PDF never blocks the event loop."""
    from generativeaiexamples_tpu.chains.loaders import load_document

    for pattern in paths:
        for path in sorted(globlib.glob(pattern)) or [pattern]:
            try:
                text = await asyncio.to_thread(load_document, path)
            except Exception as exc:
                logger.warning("source %s failed: %s", path, exc)
                yield SourceItem(content="", source=path,
                                 collection=collection, error=str(exc))
                continue
            if text.strip():
                yield SourceItem(content=text, source=path,
                                 collection=collection)


async def jsonl_source(path: str, content_key: str = "content",
                       collection: str = "default") -> AsyncIterator[SourceItem]:
    """Line-delimited JSON source (the shape Kafka topics carry in the
    reference's kafka_source_pipe; a real Kafka consumer yields the same
    SourceItems from poll loops)."""
    def read_lines():
        with open(path, "r", encoding="utf-8") as fh:
            return fh.readlines()

    for i, line in enumerate(await asyncio.to_thread(read_lines)):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as exc:
            logger.warning("%s:%d not valid json; skipped", path, i + 1)
            yield SourceItem(content="", source=f"{path}:{i + 1}",
                             collection=collection, error=str(exc))
            continue
        content = str(obj.get(content_key, ""))
        if content.strip():
            yield SourceItem(content=content,
                             source=str(obj.get("source", f"{path}:{i + 1}")),
                             collection=str(obj.get("collection", collection)))


class StreamingIngestor:
    """Drives sources through chunk → embed → store with bounded queues.

    ``store_factory(collection)`` returns the target store (the ChainContext
    `store` method fits directly); `embedder` is the in-proc TPU embedder.
    """

    def __init__(self, embedder, store_factory: Callable[[str], object],
                 splitter, embed_batch: int = 32, queue_depth: int = 64,
                 ) -> None:
        self.embedder = embedder
        self.store_factory = store_factory
        self.splitter = splitter
        self.embed_batch = embed_batch
        self.queue_depth = queue_depth
        self.stats = IngestStats()

    # ------------------------------------------------------------- pipeline

    async def run(self, sources: Sequence[AsyncIterator[SourceItem]]
                  ) -> IngestStats:
        """Run all sources to exhaustion through the staged pipeline.
        Stats are per-run (a reused ingestor starts from zero each time)."""
        self.stats = IngestStats()
        t0 = time.perf_counter()
        chunk_q: asyncio.Queue = asyncio.Queue(self.queue_depth)
        embed_q: asyncio.Queue = asyncio.Queue(self.queue_depth)

        async def pump(src: AsyncIterator[SourceItem]) -> None:
            # a broken source (missing file, dead feed) must not take the
            # pipeline down with it — count it and let the others drain
            try:
                async for item in src:
                    if item.error:
                        self.stats.errors += 1
                        continue
                    self.stats.items += 1
                    await chunk_q.put(item)
            except Exception as exc:
                self.stats.errors += 1
                logger.warning("source failed: %s", exc)

        async def chunk_stage() -> None:
            while True:
                item = await chunk_q.get()
                if item is _STOP:
                    await embed_q.put(_STOP)
                    return
                try:
                    chunks = await asyncio.to_thread(
                        self.splitter.split, item.content)
                except Exception as exc:
                    self.stats.errors += 1
                    logger.warning("chunking %s failed: %s", item.source, exc)
                    continue
                for c in chunks:
                    self.stats.chunks += 1
                    await embed_q.put(dataclasses.replace(item, content=c))

        async def embed_store_stage() -> None:
            batch: List[SourceItem] = []

            async def flush():
                if not batch:
                    return
                texts = [b.content for b in batch]
                try:
                    embs = await asyncio.to_thread(
                        self.embedder.embed_documents, texts)
                except Exception as exc:
                    self.stats.errors += len(batch)
                    logger.warning("embed batch failed: %s", exc)
                    batch.clear()
                    return
                self.stats.embedded += len(batch)
                by_coll: Dict[str, List[int]] = {}
                for i, b in enumerate(batch):
                    by_coll.setdefault(b.collection, []).append(i)
                import numpy as np
                for coll, idxs in by_coll.items():
                    docs = [Document(content=batch[i].content,
                                     metadata={"source": batch[i].source})
                            for i in idxs]
                    sel = (embs[idxs] if isinstance(embs, np.ndarray)
                           else np.stack([np.asarray(embs[i]) for i in idxs]))
                    # a failing store (dim mismatch, disk full, dead remote)
                    # must not kill the stage: under backpressure a dead
                    # consumer deadlocks every upstream put()
                    try:
                        await asyncio.to_thread(
                            self.store_factory(coll).add, docs, sel)
                    except Exception as exc:
                        self.stats.errors += len(idxs)
                        logger.warning("store %s add failed: %s", coll, exc)
                        continue
                    self.stats.stored += len(idxs)
                batch.clear()

            while True:
                item = await embed_q.get()
                if item is _STOP:
                    await flush()
                    return
                batch.append(item)
                if len(batch) >= self.embed_batch:
                    await flush()

        chunker = asyncio.create_task(chunk_stage())
        storer = asyncio.create_task(embed_store_stage())
        try:
            await asyncio.gather(*(pump(s) for s in sources))
        finally:
            # stages must always be shut down and the tail batch flushed,
            # even if a pump raised something pump() itself didn't absorb —
            # orphaned stage tasks would otherwise leak in a server loop
            await chunk_q.put(_STOP)
            await asyncio.gather(chunker, storer)
        self.stats.wall_s = time.perf_counter() - t0
        logger.info(
            "streaming ingest: %d items -> %d chunks -> %d stored "
            "(%d errors) in %.2fs", self.stats.items, self.stats.chunks,
            self.stats.stored, self.stats.errors, self.stats.wall_s)
        return self.stats

    def run_sync(self, sources: Sequence[AsyncIterator[SourceItem]]
                 ) -> IngestStats:
        return asyncio.run(self.run(sources))
