"""External vector-DB adapters: Milvus and pgvector behind the store seam.

Parity with the reference's `get_vector_db` dispatch (ref:
RAG/src/chain_server/utils.py:220-332 — branches on
``APP_VECTORSTORE_NAME`` to build a Milvus or PGVector langchain store; the
compose files run the actual services). The in-process device-resident
`retrieval.store.VectorStore` stays the default ("tpu"); these adapters give
deployments that already operate a Milvus/Postgres the same drop-in surface:
``add / search / list_sources / delete_by_source / __len__``, scores in
cosine-similarity terms.

The client objects are injected (constructor arg) and otherwise imported
lazily — `pymilvus` / `psycopg2` are NOT vendored dependencies of this
framework; a missing driver raises immediately with the package name instead
of degrading silently. Tests exercise the adapters against in-memory fakes
of the wire surface.
"""

from __future__ import annotations

import json
import logging
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from generativeaiexamples_tpu.retrieval.store import Document

logger = logging.getLogger(__name__)


class MilvusStore:
    """Milvus collection adapter (ref utils.py:253-287 Milvus branch).

    Schema: auto-id pk, float-vector field "embedding" (COSINE), varchar
    "content", JSON "metadata", varchar "source" (delete-by-source filter).
    """

    def __init__(self, dim: int, url: str = "http://localhost:19530",
                 name: str = "default", client: Any = None) -> None:
        self.dim = dim
        self.name = f"gaie_{name}"
        if client is None:
            try:
                from pymilvus import MilvusClient
            except ImportError as exc:   # pragma: no cover - env-dependent
                raise ImportError(
                    "MilvusStore needs the 'pymilvus' package (or pass a "
                    "compatible client=)") from exc
            client = MilvusClient(uri=url)
        self.client = client
        if not self.client.has_collection(self.name):
            self.client.create_collection(
                collection_name=self.name, dimension=dim,
                metric_type="COSINE", auto_id=False,
                id_type="string", max_length=64)   # uuid4 hex string pks

    def add(self, docs: Sequence[Document], embeddings: np.ndarray) -> List[str]:
        emb = np.asarray(embeddings, np.float32)
        rows, ids = [], []
        for doc, vec in zip(docs, emb):
            pk = uuid.uuid4().hex
            ids.append(pk)
            rows.append({"id": pk, "vector": vec.tolist(),
                         "content": doc.content,
                         "source": str(doc.metadata.get("source", "")),
                         "metadata": json.dumps(doc.metadata)})
        if rows:
            self.client.insert(collection_name=self.name, data=rows)
        return ids

    def search(self, query_embedding: np.ndarray, top_k: int = 4,
               score_threshold: float = 0.0
               ) -> List[Tuple[Document, float]]:
        res = self.client.search(
            collection_name=self.name,
            data=[np.asarray(query_embedding, np.float32).tolist()],
            limit=top_k, output_fields=["content", "metadata"])
        hits: List[Tuple[Document, float]] = []
        for hit in (res[0] if res else []):
            score = float(hit.get("distance", 0.0))
            if score < score_threshold:
                continue
            entity = hit.get("entity", hit)
            meta = entity.get("metadata", "{}")
            meta = json.loads(meta) if isinstance(meta, str) else dict(meta)
            hits.append((Document(content=entity.get("content", ""),
                                  metadata=meta), score))
        return hits

    def list_sources(self) -> List[str]:
        rows = self.client.query(collection_name=self.name,
                                 filter="source != ''",
                                 output_fields=["source"])
        return sorted({r["source"] for r in rows})

    def delete_by_source(self, sources: Sequence[str]) -> int:
        n = 0
        for src in sources:
            # escape the quoted value: a filename like x" || source != "
            # must not widen the filter expression
            quoted = str(src).replace("\\", "\\\\").replace('"', '\\"')
            res = self.client.delete(
                collection_name=self.name,
                filter=f'source == "{quoted}"')
            n += int(res.get("delete_count", 0)) if isinstance(res, dict) \
                else len(res or [])
        return n

    def __len__(self) -> int:
        rows = self.client.query(collection_name=self.name,
                                 output_fields=["count(*)"])
        return int(rows[0]["count(*)"]) if rows else 0


class PgVectorStore:
    """Postgres + pgvector adapter (ref utils.py:289-332 PGVector branch).

    One table per collection: (id uuid, content text, source text,
    metadata jsonb, embedding vector(dim)); cosine distance operator <=>.
    """

    def __init__(self, dim: int, url: str = "", name: str = "default",
                 conn: Any = None) -> None:
        self.dim = dim
        self.table = f"gaie_{name}"
        if conn is None:
            try:
                import psycopg2
            except ImportError as exc:   # pragma: no cover - env-dependent
                raise ImportError(
                    "PgVectorStore needs the 'psycopg2' package (or pass a "
                    "compatible conn=)") from exc
            conn = psycopg2.connect(url)
        self.conn = conn
        with self.conn.cursor() as cur:
            cur.execute("CREATE EXTENSION IF NOT EXISTS vector")
            cur.execute(
                f"CREATE TABLE IF NOT EXISTS {self.table} ("
                f"id text PRIMARY KEY, content text, source text, "
                f"metadata jsonb, embedding vector({dim}))")
        self.conn.commit()

    @staticmethod
    def _vec_literal(vec: np.ndarray) -> str:
        return "[" + ",".join(f"{x:.8f}" for x in np.asarray(vec)) + "]"

    def add(self, docs: Sequence[Document], embeddings: np.ndarray) -> List[str]:
        ids = []
        with self.conn.cursor() as cur:
            for doc, vec in zip(docs, np.asarray(embeddings, np.float32)):
                pk = uuid.uuid4().hex
                ids.append(pk)
                cur.execute(
                    f"INSERT INTO {self.table} "
                    f"(id, content, source, metadata, embedding) "
                    f"VALUES (%s, %s, %s, %s, %s)",
                    (pk, doc.content, str(doc.metadata.get("source", "")),
                     json.dumps(doc.metadata), self._vec_literal(vec)))
        self.conn.commit()
        return ids

    def search(self, query_embedding: np.ndarray, top_k: int = 4,
               score_threshold: float = 0.0
               ) -> List[Tuple[Document, float]]:
        lit = self._vec_literal(query_embedding)
        with self.conn.cursor() as cur:
            cur.execute(
                f"SELECT content, metadata, 1 - (embedding <=> %s) AS score "
                f"FROM {self.table} ORDER BY embedding <=> %s LIMIT %s",
                (lit, lit, top_k))
            rows = cur.fetchall()
        hits = []
        for content, meta, score in rows:
            if float(score) < score_threshold:
                continue
            meta = json.loads(meta) if isinstance(meta, str) else dict(meta)
            hits.append((Document(content=content, metadata=meta),
                         float(score)))
        return hits

    def list_sources(self) -> List[str]:
        with self.conn.cursor() as cur:
            cur.execute(f"SELECT DISTINCT source FROM {self.table} "
                        f"WHERE source != ''")
            return sorted(r[0] for r in cur.fetchall())

    def delete_by_source(self, sources: Sequence[str]) -> int:
        n = 0
        with self.conn.cursor() as cur:
            for src in sources:
                cur.execute(f"DELETE FROM {self.table} WHERE source = %s",
                            (src,))
                n += cur.rowcount
        self.conn.commit()
        return n

    def __len__(self) -> int:
        with self.conn.cursor() as cur:
            cur.execute(f"SELECT count(*) FROM {self.table}")
            return int(cur.fetchone()[0])


class _EsRest:
    """Minimal Elasticsearch REST client (urllib; no vendored driver)."""

    def __init__(self, url: str) -> None:
        self.url = (url or "http://localhost:9200").rstrip("/")

    def request(self, method: str, path: str, body=None) -> Dict:
        import urllib.error
        import urllib.request

        if isinstance(body, str):            # NDJSON (_bulk)
            data = body.encode()
            ctype = "application/x-ndjson"
        else:
            data = json.dumps(body).encode() if body is not None else None
            ctype = "application/json"
        req = urllib.request.Request(
            f"{self.url}{path}", method=method, data=data,
            headers={"Content-Type": ctype})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as exc:
            # surface the ES error BODY (e.g. resource_already_exists_
            # exception) — HTTPError's str() is just "HTTP Error 400"
            detail = exc.read().decode("utf-8", "replace")[:500]
            raise RuntimeError(
                f"elasticsearch {method} {path} -> {exc.code}: "
                f"{detail}") from exc
        return json.loads(payload) if payload else {}


class ElasticsearchStore:
    """Elasticsearch dense-vector kNN adapter (ref: RAG/examples/
    local_deploy/docker-compose-vectordb.yaml:86-104 runs elasticsearch as a
    first-class store next to Milvus/pgvector).

    One index per collection: dense_vector (cosine) + content/source/
    metadata fields; search is the ES 8 top-level ``knn`` query; deletes go
    through ``_delete_by_query`` on the source keyword. The wire surface is
    a single ``request(method, path, body)`` callable, so tests inject an
    in-memory fake and deployments get the real REST endpoint with zero
    extra dependencies."""

    def __init__(self, dim: int, url: str = "http://localhost:9200",
                 name: str = "default", client: Any = None) -> None:
        self.dim = dim
        self.index = f"gaie_{name}".lower()
        self.client = client if client is not None else _EsRest(url)
        try:
            self.client.request("PUT", f"/{self.index}", {
                "mappings": {"properties": {
                    "embedding": {"type": "dense_vector", "dims": dim,
                                  "index": True, "similarity": "cosine"},
                    "content": {"type": "text"},
                    "source": {"type": "keyword"},
                    "metadata": {"type": "object", "enabled": False},
                }}})
        except Exception as exc:
            # idempotent reconnect (Milvus/pgvector adapters' semantics):
            # an existing index is fine, anything else is a real failure
            if "resource_already_exists" not in str(exc):
                raise

    def add(self, docs: Sequence[Document], embeddings: np.ndarray) -> List[str]:
        ids = []
        lines = []
        for doc, vec in zip(docs, np.asarray(embeddings, np.float32)):
            pk = uuid.uuid4().hex
            ids.append(pk)
            lines.append(json.dumps({"index": {"_id": pk}}))
            lines.append(json.dumps({
                "embedding": vec.tolist(), "content": doc.content,
                "source": str(doc.metadata.get("source", "")),
                "metadata": doc.metadata}))
        if lines:
            # one _bulk round trip for the whole batch, not one per chunk
            self.client.request("POST", f"/{self.index}/_bulk",
                                "\n".join(lines) + "\n")
            self.client.request("POST", f"/{self.index}/_refresh")
        return ids

    def search(self, query_embedding: np.ndarray, top_k: int = 4,
               score_threshold: float = 0.0
               ) -> List[Tuple[Document, float]]:
        res = self.client.request("POST", f"/{self.index}/_search", {
            "knn": {"field": "embedding",
                    "query_vector": np.asarray(query_embedding,
                                               np.float32).tolist(),
                    "k": top_k, "num_candidates": max(top_k * 10, 100)},
            "_source": ["content", "metadata"], "size": top_k})
        hits = []
        for h in res.get("hits", {}).get("hits", []):
            # ES kNN cosine score is (1 + cos) / 2, already in [0, 1] —
            # the same range the in-proc store reports
            score = float(h.get("_score", 0.0))
            if score < score_threshold:
                continue
            src = h.get("_source", {})
            hits.append((Document(content=src.get("content", ""),
                                  metadata=dict(src.get("metadata") or {})),
                         score))
        return hits

    def list_sources(self) -> List[str]:
        res = self.client.request("POST", f"/{self.index}/_search", {
            "size": 0, "aggs": {"sources": {
                "terms": {"field": "source", "size": 10000}}}})
        buckets = (res.get("aggregations", {}).get("sources", {})
                   .get("buckets", []))
        return sorted(b["key"] for b in buckets if b.get("key"))

    def delete_by_source(self, sources: Sequence[str]) -> int:
        res = self.client.request(
            "POST", f"/{self.index}/_delete_by_query?refresh=true",
            {"query": {"terms": {"source": [str(s) for s in sources]}}})
        return int(res.get("deleted", 0))

    def __len__(self) -> int:
        res = self.client.request("GET", f"/{self.index}/_count")
        return int(res.get("count", 0))


def make_store(dim: int, config, name: str = "default",
               client: Any = None):
    """Backend dispatch on VectorStoreConfig.name (ref utils.py:220-250 +
    the elasticsearch compose service): "tpu" (default, in-proc
    device-resident) | "milvus" | "pgvector" | "elasticsearch"."""
    backend = (config.name or "tpu").lower()
    if backend in ("tpu", "inproc", "default"):
        from generativeaiexamples_tpu.retrieval.store import VectorStore

        return VectorStore(dim=dim, index_type=config.index_type,
                           nlist=config.nlist, nprobe=config.nprobe,
                           name=name)
    if backend == "milvus":
        return MilvusStore(dim=dim, url=config.url, name=name, client=client)
    if backend == "pgvector":
        return PgVectorStore(dim=dim, url=config.url, name=name, conn=client)
    if backend in ("elasticsearch", "es"):
        return ElasticsearchStore(dim=dim, url=config.url, name=name,
                                  client=client)
    raise ValueError(f"unknown vector store backend {config.name!r} "
                     f"(expected tpu|milvus|pgvector|elasticsearch)")
