"""BM25 lexical index — the sparse half of hybrid retrieval.

The reference's agentic notebook builds an EnsembleRetriever over BM25 +
dense FAISS (ref: RAG/notebooks/langchain/agentic_rag_with_nemo_retriever_
nim.ipynb, "BM25Retriever + FAISS" hybrid, lines 227-235). This module
provides the BM25 side in-tree (Okapi BM25, k1/b defaults per the classic
formulation) plus reciprocal-rank fusion for the ensemble.
"""

from __future__ import annotations

import math
import re
from collections import Counter
from typing import Dict, List, Sequence, Tuple

_TOKEN = re.compile(r"[a-z0-9]+")


def _tokenize(text: str) -> List[str]:
    return _TOKEN.findall(text.lower())


class BM25Index:
    def __init__(self, k1: float = 1.5, b: float = 0.75) -> None:
        self.k1 = k1
        self.b = b
        self._docs: List[str] = []
        self._tf: List[Counter] = []
        self._df: Counter = Counter()
        self._lengths: List[int] = []

    def add(self, texts: Sequence[str]) -> None:
        for text in texts:
            toks = _tokenize(text)
            tf = Counter(toks)
            self._docs.append(text)
            self._tf.append(tf)
            self._lengths.append(len(toks))
            for term in tf:
                self._df[term] += 1

    def search(self, query: str, top_k: int = 4) -> List[Tuple[int, float]]:
        """Top-k (doc_index, score)."""
        if not self._docs:
            return []
        n = len(self._docs)
        avg_len = sum(self._lengths) / n
        scores = [0.0] * n
        for term in _tokenize(query):
            df = self._df.get(term)
            if not df:
                continue
            idf = math.log(1 + (n - df + 0.5) / (df + 0.5))
            for i, tf in enumerate(self._tf):
                f = tf.get(term)
                if not f:
                    continue
                denom = f + self.k1 * (1 - self.b + self.b * self._lengths[i] / avg_len)
                scores[i] += idf * f * (self.k1 + 1) / denom
        order = sorted(range(n), key=lambda i: -scores[i])[:top_k]
        return [(i, scores[i]) for i in order if scores[i] > 0]


def reciprocal_rank_fusion(rankings: Sequence[Sequence[int]], k: int = 60,
                           top_k: int = 4) -> List[int]:
    """Fuse multiple ranked id lists (the EnsembleRetriever combiner)."""
    scores: Dict[int, float] = {}
    for ranking in rankings:
        for rank, doc_id in enumerate(ranking):
            scores[doc_id] = scores.get(doc_id, 0.0) + 1.0 / (k + rank + 1)
    return sorted(scores, key=lambda d: -scores[d])[:top_k]
