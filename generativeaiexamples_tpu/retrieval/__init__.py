"""Retrieval: on-device vector search, BM25, text splitting, doc store.

Replaces the reference's storage layer L2 (Milvus-GPU / pgvector / FAISS,
ref docker-compose-vectordb.yaml; client factories utils.py:220-332) with an
in-process store whose similarity search is a jitted TPU matmul — embeddings
at e5 scale make brute-force over millions of vectors a single MXU-friendly
GEMM, with an IVF mode mirroring the GPU_IVF_FLAT config knobs
(configuration.py:42-44).
"""

from generativeaiexamples_tpu.retrieval.store import Document, VectorStore  # noqa: F401
from generativeaiexamples_tpu.retrieval.text_splitter import TokenTextSplitter  # noqa: F401
from generativeaiexamples_tpu.retrieval.bm25 import BM25Index  # noqa: F401
