"""In-process vector store with TPU matmul search (exact + IVF).

API parity with the vector-store operations the chain server exercises
(ref: utils.py — create_vectorstore_langchain:288, get_docs_vectorstore:492,
del_docs_vectorstore:532; search with top-k + score threshold,
basic_rag/langchain/chains.py:156-167): add / search / list-sources /
delete-by-source, plus collection semantics.

Design: vectors live in a device-resident matrix grown in power-of-two
blocks (static shapes → one compiled search kernel per capacity step).
Exact search = one GEMM + top-k; IVF mode (`GPU_IVF_FLAT` parity,
configuration.py:42-44) clusters with on-device k-means and probes
``nprobe`` cells. Cosine scores in [−1, 1] are mapped to the [0, 1] range
the reference's score_threshold=0.25 default expects.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class Document:
    content: str
    metadata: Dict[str, object] = field(default_factory=dict)
    doc_id: str = field(default_factory=lambda: uuid.uuid4().hex)


@partial(jax.jit, static_argnames=("k",))
def _topk_scores(matrix: jnp.ndarray, query: jnp.ndarray, valid: jnp.ndarray,
                 k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """scores = matrix @ query, invalid rows masked; returns (vals, idx)."""
    scores = matrix @ query  # (N,)
    scores = jnp.where(valid, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("nprobe", "k"))
def _ivf_search(matrix: jnp.ndarray, centroids: jnp.ndarray,
                assignments: jnp.ndarray, valid: jnp.ndarray,
                query: jnp.ndarray, nprobe: int, k: int):
    cell_scores = centroids @ query                      # (nlist,)
    probe = jax.lax.top_k(cell_scores, nprobe)[1]        # (nprobe,)
    in_probe = (assignments[:, None] == probe[None, :]).any(axis=1)
    scores = matrix @ query
    scores = jnp.where(valid & in_probe, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


class VectorStore:
    """One named collection (ref collection_name semantics, utils.py:240)."""

    def __init__(self, dim: int, index_type: str = "exact", nlist: int = 64,
                 nprobe: int = 16, name: str = "default") -> None:
        self.dim = dim
        self.name = name
        self.index_type = index_type
        self.nlist = nlist
        self.nprobe = min(nprobe, nlist)
        self._lock = threading.Lock()
        self._docs: List[Optional[Document]] = []
        self._capacity = 0
        self._matrix: Optional[jnp.ndarray] = None   # (cap, dim) on device
        self._valid_host = np.zeros((0,), bool)
        self._centroids: Optional[jnp.ndarray] = None
        self._assignments: Optional[jnp.ndarray] = None
        self._ivf_dirty = True

    # ------------------------------------------------------------------ add

    def add(self, docs: Sequence[Document], embeddings: np.ndarray) -> List[str]:
        if len(docs) != len(embeddings):
            raise ValueError("docs/embeddings length mismatch")
        with self._lock:
            n_old = len(self._docs)
            n_new = n_old + len(docs)
            if n_new > self._capacity:
                cap = max(256, self._capacity)
                while cap < n_new:
                    cap *= 2
                new_matrix = np.zeros((cap, self.dim), np.float32)
                if self._matrix is not None:
                    new_matrix[:n_old] = np.asarray(self._matrix)[:n_old]
                self._capacity = cap
                self._matrix = jnp.asarray(new_matrix)
                self._valid_host = np.resize(self._valid_host, cap)
                self._valid_host[n_old:] = False
            emb = np.asarray(embeddings, np.float32)
            emb = emb / np.clip(np.linalg.norm(emb, axis=1, keepdims=True), 1e-9, None)
            self._matrix = jax.lax.dynamic_update_slice(
                self._matrix, jnp.asarray(emb), (n_old, 0))
            self._docs.extend(docs)
            self._valid_host[n_old:n_new] = True
            self._ivf_dirty = True
            return [d.doc_id for d in docs]

    # --------------------------------------------------------------- search

    def search(self, query_embedding: np.ndarray, top_k: int = 4,
               score_threshold: float = 0.0) -> List[Tuple[Document, float]]:
        with self._lock:
            if not self._docs or self._matrix is None:
                return []
            q = jnp.asarray(np.asarray(query_embedding, np.float32))
            q = q / jnp.linalg.norm(q).clip(1e-9)
            valid = jnp.asarray(self._valid_host)
            k = min(top_k, self._capacity)
            # gate on *live* rows (deleted entries stay as None placeholders);
            # an all-deleted store must fall through to brute force rather
            # than k-means over zero vectors
            n_live = int(np.count_nonzero(self._valid_host[: self._capacity]))
            if self.index_type == "ivf" and n_live > self.nlist * 4:
                self._maybe_build_ivf()
                vals, idx = _ivf_search(self._matrix, self._centroids,
                                        self._assignments, valid, q,
                                        self.nprobe, k)
            else:
                vals, idx = _topk_scores(self._matrix, q, valid, k)
        vals = np.asarray(vals)
        idx = np.asarray(idx)
        out: List[Tuple[Document, float]] = []
        for score, i in zip(vals, idx):
            if not np.isfinite(score):
                continue
            doc = self._docs[int(i)]
            if doc is None:
                continue
            relevance = (float(score) + 1.0) / 2.0  # cosine → [0,1]
            if relevance >= score_threshold:
                out.append((doc, relevance))
        return out

    # ------------------------------------------------------------------ IVF

    def _maybe_build_ivf(self, iters: int = 8) -> None:
        """On-device mini k-means over the current vectors (caller holds lock)."""
        if not self._ivf_dirty and self._centroids is not None:
            return
        data = np.asarray(self._matrix)[self._valid_host[: self._capacity]]
        rng = np.random.default_rng(0)
        seeds = data[rng.choice(len(data), self.nlist, replace=len(data) < self.nlist)]
        centroids = jnp.asarray(seeds)
        mat = jnp.asarray(data)

        @jax.jit
        def step(c):
            assign = jnp.argmax(mat @ c.T, axis=1)
            onehot = jax.nn.one_hot(assign, self.nlist, dtype=jnp.float32)
            sums = onehot.T @ mat
            counts = onehot.sum(axis=0)[:, None]
            new_c = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), c)
            norm = jnp.linalg.norm(new_c, axis=1, keepdims=True).clip(1e-9)
            return new_c / norm

        for _ in range(iters):
            centroids = step(centroids)
        full_assign = np.full((self._capacity,), -1, np.int32)
        assign = np.asarray(jnp.argmax(mat @ centroids.T, axis=1))
        full_assign[np.flatnonzero(self._valid_host[: self._capacity])] = assign
        self._centroids = centroids
        self._assignments = jnp.asarray(full_assign)
        self._ivf_dirty = False

    # ------------------------------------------------------------ documents

    def list_sources(self) -> List[str]:
        """Distinct source filenames (ref get_docs_vectorstore_langchain,
        utils.py:492-530 returns uploaded file names)."""
        with self._lock:
            seen = []
            for d in self._docs:
                if d is None:
                    continue
                src = str(d.metadata.get("source", ""))
                if src and src not in seen:
                    seen.append(src)
            return seen

    def delete_by_source(self, sources: Sequence[str]) -> int:
        """Remove all chunks from the named source files (ref
        del_docs_vectorstore_langchain, utils.py:532-560)."""
        targets = set(sources)
        removed = 0
        with self._lock:
            for i, d in enumerate(self._docs):
                if d is not None and str(d.metadata.get("source", "")) in targets:
                    self._docs[i] = None
                    self._valid_host[i] = False
                    removed += 1
            self._ivf_dirty = True
        return removed

    def __len__(self) -> int:
        return sum(1 for d in self._docs if d is not None)
