"""In-process vector store with TPU matmul search (exact + IVF).

API parity with the vector-store operations the chain server exercises
(ref: utils.py — create_vectorstore_langchain:288, get_docs_vectorstore:492,
del_docs_vectorstore:532; search with top-k + score threshold,
basic_rag/langchain/chains.py:156-167): add / search / list-sources /
delete-by-source, plus collection semantics.

Design: vectors live in a device-resident matrix grown in power-of-two
blocks (static shapes → one compiled search kernel per capacity step).
Exact search = one GEMM + top-k. IVF mode (`GPU_IVF_FLAT` parity,
configuration.py:42-44) clusters with on-device k-means into a cell-major
(nlist, cell_cap, dim) layout and gathers ONLY the ``nprobe`` probed
cells' vectors per query — bounded work per search regardless of N, at
the cost of one extra padded copy of the vectors. k-means retrains only
when the store doubles; adds in between assign to existing centroids.
Cosine scores in [−1, 1] are mapped to the [0, 1] range the reference's
score_threshold=0.25 default expects.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class Document:
    content: str
    metadata: Dict[str, object] = field(default_factory=dict)
    doc_id: str = field(default_factory=lambda: uuid.uuid4().hex)


@partial(jax.jit, static_argnames=("k",))
def _topk_scores(matrix: jnp.ndarray, query: jnp.ndarray, valid: jnp.ndarray,
                 k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """scores = matrix @ query, invalid rows masked; returns (vals, idx)."""
    scores = matrix @ query  # (N,)
    scores = jnp.where(valid, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, static_argnames=("nprobe", "k"))
def _ivf_search(grouped: jnp.ndarray, grouped_ids: jnp.ndarray,
                centroids: jnp.ndarray, valid: jnp.ndarray,
                query: jnp.ndarray, nprobe: int, k: int):
    """Real IVF: score centroids, gather ONLY the probed cells' vectors
    (cell-major (nlist, cell_cap, dim) layout), GEMM those against the
    query. Work per query is nprobe*cell_cap*dim regardless of N — the
    bounded-probe contract of Milvus GPU_IVF_FLAT — instead of the full
    N*dim GEMM the exact path pays. Returns (scores, original row ids);
    padding and deleted rows come back as -inf."""
    cell_scores = centroids @ query                      # (nlist,)
    probe = jax.lax.top_k(cell_scores, nprobe)[1]        # (nprobe,)
    sub = grouped[probe]                                 # (nprobe, cap, dim)
    ids = grouped_ids[probe]                             # (nprobe, cap)
    scores = jnp.einsum("pcd,d->pc", sub, query)
    # ids of -1 mark padding; the wrapped gather valid[-1] is masked anyway
    ok = (ids >= 0) & valid[ids]
    scores = jnp.where(ok, scores, -jnp.inf)
    vals, flat = jax.lax.top_k(scores.reshape(-1), k)
    return vals, ids.reshape(-1)[flat]


class VectorStore:
    """One named collection (ref collection_name semantics, utils.py:240)."""

    def __init__(self, dim: int, index_type: str = "exact", nlist: int = 64,
                 nprobe: int = 16, name: str = "default") -> None:
        self.dim = dim
        self.name = name
        self.index_type = index_type
        self.nlist = nlist
        self.nprobe = min(nprobe, nlist)
        self._lock = threading.Lock()
        self._docs: List[Optional[Document]] = []
        self._capacity = 0
        self._matrix: Optional[jnp.ndarray] = None   # (cap, dim) on device
        self._valid_host = np.zeros((0,), bool)
        self._centroids: Optional[jnp.ndarray] = None
        self._grouped: Optional[jnp.ndarray] = None      # (nlist, cap, dim)
        self._grouped_ids: Optional[jnp.ndarray] = None  # (nlist, cap) row ids
        self._ivf_dirty = True
        self._ivf_trained_n = 0     # live rows at the last k-means training
        self._ivf_upto = 0          # docs rows already inserted into grouped
        self._cell_fill: Optional[np.ndarray] = None     # (nlist,) host

    # ------------------------------------------------------------------ add

    def add(self, docs: Sequence[Document], embeddings: np.ndarray) -> List[str]:
        if len(docs) != len(embeddings):
            raise ValueError("docs/embeddings length mismatch")
        with self._lock:
            n_old = len(self._docs)
            n_new = n_old + len(docs)
            if n_new > self._capacity:
                cap = max(256, self._capacity)
                while cap < n_new:
                    cap *= 2
                new_matrix = np.zeros((cap, self.dim), np.float32)
                if self._matrix is not None:
                    new_matrix[:n_old] = np.asarray(self._matrix)[:n_old]
                self._capacity = cap
                self._matrix = jnp.asarray(new_matrix)
                self._valid_host = np.resize(self._valid_host, cap)
                self._valid_host[n_old:] = False
            emb = np.asarray(embeddings, np.float32)
            emb = emb / np.clip(np.linalg.norm(emb, axis=1, keepdims=True), 1e-9, None)
            self._matrix = jax.lax.dynamic_update_slice(
                self._matrix, jnp.asarray(emb), (n_old, 0))
            self._docs.extend(docs)
            self._valid_host[n_old:n_new] = True
            self._ivf_dirty = True
            return [d.doc_id for d in docs]

    # --------------------------------------------------------------- search

    def search(self, query_embedding: np.ndarray, top_k: int = 4,
               score_threshold: float = 0.0) -> List[Tuple[Document, float]]:
        # Under the lock: SNAPSHOT only. The matrix/grouped arrays are
        # replaced (never mutated) by add(), and _valid_host is copied to
        # device here, so the scoring below runs on a consistent view —
        # concurrent searches (N RAG clients + lookahead threads, the
        # pipelined dataplane's normal state) no longer serialize their
        # device compute on the store lock.
        with self._lock:
            if not self._docs or self._matrix is None:
                return []
            valid = jnp.asarray(self._valid_host)
            matrix = self._matrix
            k = min(top_k, self._capacity)
            # gate on *live* rows (deleted entries stay as None placeholders);
            # an all-deleted store must fall through to brute force rather
            # than k-means over zero vectors
            n_live = int(np.count_nonzero(self._valid_host[: self._capacity]))
            use_ivf = self.index_type == "ivf" and n_live > self.nlist * 4
            if use_ivf:
                self._maybe_build_ivf()
                grouped, grouped_ids = self._grouped, self._grouped_ids
                centroids = self._centroids
        q = jnp.asarray(np.asarray(query_embedding, np.float32))
        q = q / jnp.linalg.norm(q).clip(1e-9)
        if use_ivf:
            k = min(k, self.nprobe * grouped.shape[1])
            vals, idx = _ivf_search(grouped, grouped_ids, centroids, valid, q,
                                    self.nprobe, k)
        else:
            vals, idx = _topk_scores(matrix, q, valid, k)
        vals = np.asarray(vals)
        idx = np.asarray(idx)
        out: List[Tuple[Document, float]] = []
        for score, i in zip(vals, idx):
            if not np.isfinite(score):
                continue
            doc = self._docs[int(i)]
            if doc is None:
                continue
            relevance = (float(score) + 1.0) / 2.0  # cosine → [0,1]
            if relevance >= score_threshold:
                out.append((doc, relevance))
        return out

    # ------------------------------------------------------------------ IVF

    def _maybe_build_ivf(self, iters: int = 8) -> None:
        """(Re)build the probe index (caller holds lock).

        k-means retrains only when the store has doubled since the last
        training (classic IVF: train once, later adds just assign to the
        nearest existing centroid) — so streaming ingest doesn't re-cluster
        on every batch. Every dirty build regroups vectors into the
        cell-major (nlist, cell_cap, dim) layout `_ivf_search` gathers
        from; cell_cap is the largest cell rounded up to a power of two
        (bounded compile variants)."""
        if not self._ivf_dirty and self._centroids is not None:
            return
        n_docs = len(self._docs)
        n_live = int(np.count_nonzero(self._valid_host[: self._capacity]))
        if self._centroids is None or n_live >= 2 * self._ivf_trained_n:
            self._full_build_ivf(iters)
        else:
            self._insert_new_rows_ivf()
        self._ivf_upto = n_docs
        self._ivf_dirty = False

    def _full_build_ivf(self, iters: int) -> None:
        """Train k-means and regroup everything (first build, or the store
        doubled since the last training)."""
        live_ix = np.flatnonzero(self._valid_host[: self._capacity])
        data = np.asarray(self._matrix)[live_ix]
        n_live = len(live_ix)
        rng = np.random.default_rng(0)
        seeds = data[rng.choice(n_live, self.nlist,
                                replace=n_live < self.nlist)]
        centroids = jnp.asarray(seeds)
        mat = jnp.asarray(data)

        @jax.jit
        def step(c):
            assign = jnp.argmax(mat @ c.T, axis=1)
            onehot = jax.nn.one_hot(assign, self.nlist, dtype=jnp.float32)
            sums = onehot.T @ mat
            counts = onehot.sum(axis=0)[:, None]
            new_c = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), c)
            norm = jnp.linalg.norm(new_c, axis=1, keepdims=True).clip(1e-9)
            return new_c / norm

        for _ in range(iters):
            centroids = step(centroids)
        self._centroids = centroids
        self._ivf_trained_n = n_live
        # capacity-BALANCED assignment: raw k-means cells skew badly on
        # clustered data (measured max cell 8x the mean at 1M rows), and
        # probe work scales with the LARGEST cell — an unbalanced index
        # gathers a quarter of the corpus and loses to the exact GEMM.
        # Rows overflowing a full cell spill to their next-nearest
        # centroid (classic balanced k-means), bounding cell_cap ~2x mean.
        assign = self._balanced_assign(data)
        counts = np.bincount(assign, minlength=self.nlist)
        cell_cap = 1
        while cell_cap < max(int(counts.max()), 1):
            cell_cap *= 2
        grouped = np.zeros((self.nlist, cell_cap, self.dim), np.float32)
        grouped_ids = np.full((self.nlist, cell_cap), -1, np.int32)
        order = np.argsort(assign, kind="stable")
        sorted_assign = assign[order]
        starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
        pos = np.arange(n_live) - starts[sorted_assign]
        grouped[sorted_assign, pos] = data[order]
        grouped_ids[sorted_assign, pos] = live_ix[order]
        self._grouped = jnp.asarray(grouped)
        self._grouped_ids = jnp.asarray(grouped_ids)
        self._cell_fill = counts.astype(np.int64)

    _BALANCE_FACTOR = 2.0    # cell capacity as a multiple of the mean
    _SPILL_CHOICES = 4       # nearest centroids considered per row

    def _cell_capacity(self, n_live: int) -> int:
        return max(8, int(self._BALANCE_FACTOR * -(-n_live // self.nlist)))

    def _top_centroids(self, data: np.ndarray) -> np.ndarray:
        """(N, _SPILL_CHOICES) nearest-centroid ranking, chunked on device."""
        K = min(self._SPILL_CHOICES, self.nlist)
        out = np.empty((len(data), K), np.int32)
        step_n = 65536
        for s in range(0, len(data), step_n):
            block = jnp.asarray(data[s:s + step_n])
            _, ix = jax.lax.top_k(block @ self._centroids.T, K)
            out[s:s + step_n] = np.asarray(ix)
        return out

    def _balanced_assign(self, data: np.ndarray) -> np.ndarray:
        """Assign rows to cells with a hard per-cell capacity: first-choice
        placement in distance order, overflow spills to the next-nearest
        choice, stragglers land in the emptiest cells."""
        n = len(data)
        cap = self._cell_capacity(n)
        choices = self._top_centroids(data)
        assign = np.full((n,), -1, np.int32)
        counts = np.zeros((self.nlist,), np.int64)
        for r in range(choices.shape[1]):
            undone = np.flatnonzero(assign < 0)
            if len(undone) == 0:
                break
            cand = choices[undone, r]
            order = np.argsort(cand, kind="stable")
            rows, cells = undone[order], cand[order]
            starts = np.searchsorted(cells, np.arange(self.nlist))
            ends = np.searchsorted(cells, np.arange(self.nlist) + 1)
            for c in range(self.nlist):
                free = cap - counts[c]
                if free <= 0 or starts[c] == ends[c]:
                    continue
                take = rows[starts[c]: min(ends[c], starts[c] + free)]
                assign[take] = c
                counts[c] += len(take)
        leftovers = np.flatnonzero(assign < 0)
        for j in leftovers:       # all top choices full: emptiest cell
            c = int(np.argmin(counts))
            assign[j] = c
            counts[c] += 1
        return assign

    def _insert_new_rows_ivf(self) -> None:
        """Incremental build: assign ONLY rows added since the last build
        to their nearest centroid and scatter them into the grouped layout
        on device — O(batch) work per add cycle, not O(N) (classic IVF add
        semantics; a full regroup per upload would make an alternating
        upload/query workload quadratic)."""
        new_ix = np.flatnonzero(
            self._valid_host[self._ivf_upto: len(self._docs)])
        if len(new_ix) == 0:
            return     # deletes only: the search-time valid mask covers it
        new_ix = (new_ix + self._ivf_upto).astype(np.int32)
        vecs = self._matrix[jnp.asarray(new_ix)]         # device gather
        n_live = int(np.count_nonzero(self._valid_host[: self._capacity]))
        cap_soft = self._cell_capacity(n_live)
        choices = self._top_centroids(np.asarray(vecs))
        # slot per new row: its nearest cell with balance headroom (spill
        # to later choices, then the emptiest cell — same policy as the
        # full build, so incremental adds can't re-skew the index)
        assign = np.empty((len(new_ix),), np.int32)
        slots = np.empty_like(assign)
        fill = self._cell_fill
        for j in range(len(new_ix)):        # O(batch) python, batch-sized
            for c in choices[j]:
                if fill[c] < cap_soft:
                    break
            else:
                c = int(np.argmin(fill))
            assign[j] = c
            slots[j] = fill[c]
            fill[c] += 1
        cap = self._grouped.shape[1]
        if int(fill.max()) > cap:
            new_cap = cap
            while new_cap < int(fill.max()):
                new_cap *= 2
            self._grouped = jnp.pad(
                self._grouped, ((0, 0), (0, new_cap - cap), (0, 0)))
            self._grouped_ids = jnp.pad(
                self._grouped_ids, ((0, 0), (0, new_cap - cap)),
                constant_values=-1)
        a = jnp.asarray(assign)
        s = jnp.asarray(slots)
        self._grouped = self._grouped.at[a, s].set(vecs)
        self._grouped_ids = self._grouped_ids.at[a, s].set(
            jnp.asarray(new_ix))

    # ------------------------------------------------------------ documents

    def list_sources(self) -> List[str]:
        """Distinct source filenames (ref get_docs_vectorstore_langchain,
        utils.py:492-530 returns uploaded file names)."""
        with self._lock:
            seen = []
            for d in self._docs:
                if d is None:
                    continue
                src = str(d.metadata.get("source", ""))
                if src and src not in seen:
                    seen.append(src)
            return seen

    def delete_by_source(self, sources: Sequence[str]) -> int:
        """Remove all chunks from the named source files (ref
        del_docs_vectorstore_langchain, utils.py:532-560)."""
        targets = set(sources)
        removed = 0
        with self._lock:
            for i, d in enumerate(self._docs):
                if d is not None and str(d.metadata.get("source", "")) in targets:
                    self._docs[i] = None
                    self._valid_host[i] = False
                    removed += 1
            # no IVF rebuild: the search-time valid mask hides deleted rows;
            # they just occupy probe slots until the next add-triggered build
        return removed

    def __len__(self) -> int:
        return sum(1 for d in self._docs if d is not None)
