"""generativeaiexamples_tpu — a TPU-native generative-AI application framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of
NVIDIA's GenerativeAIExamples (reference: /root/reference): an enterprise RAG
suite (chain-orchestration server, pluggable RAG pipelines, LoRA/SFT
fine-tuning, evaluation + observability) — with the external GPU model-serving
containers (NIM/TRT-LLM, NeMo Retriever, Milvus-GPU) replaced by **in-tree
TPU engines**: a continuous-batching LLM server, jit-compiled bi-encoder /
cross-encoder services, and an on-device vector search, all sharded over a
`jax.sharding.Mesh`.

Layer map (cf. reference docs/architecture.md:23-43):

    playground/   web UI                  (ref: RAG/src/rag_playground)
    server/       chain server REST+SSE   (ref: RAG/src/chain_server/server.py)
    chains/       pluggable RAG examples  (ref: RAG/examples/{basic,advanced}_rag)
    engine/       TPU LLM serving         (replaces NIM, docker-compose-nim-ms.yaml:2-28)
    encoders/     embed + rerank services (replaces NeMo Retriever NIMs)
    retrieval/    vector search on TPU    (replaces Milvus GPU)
    train/        LoRA/SFT trainer        (replaces NeMo/Megatron containers)
    models/ ops/ parallel/                TPU compute foundation
    core/ observability/ eval/            config, tracing, evaluation
"""

__version__ = "0.1.0"
