"""Cross-request micro-batching for the encoder TPU programs.

The LLM engine batches continuously (engine/scheduler.py), but the encoder
side of the RAG dataplane was per-request: every `embed_queries` call from a
chain — a batch of ONE query — paid a full TPU dispatch (~90 ms of
per-dispatch overhead on a remote-attached chip, regardless of batch size).
Under N concurrent RAG requests that is N serialized dispatches for work the
MXU could eat in one.

`MicroBatcher` is the encoder-side analogue of continuous batching, the
stage-scheduling fix RAGO (arxiv 2503.14649) identifies as the dominant
lever in RAG serving: concurrent callers enqueue their items and block on a
future; a worker thread coalesces everything that arrives within a small
wait window (or until the batch is full) into ONE dispatch of the wrapped
function, then routes each caller's slice of the results back. N in-flight
RAG requests now cost ~1 encoder dispatch instead of N.

Semantics:

  * a submission is never split across dispatches — result routing is a
    contiguous span of the batch output (the wrapped fn chunks internally
    past its own max batch, exactly as before);
  * the window closes EARLY when `max_items` fill, so a saturated queue
    dispatches back-to-back with zero added latency;
  * a lone caller waits at most `window_s` (default 2 ms — noise next to
    the ~100 ms dispatch it rides);
  * a dispatch failure propagates to every caller in that batch and the
    worker keeps serving (no poisoned queue).

Observability: per-submission queue wait and per-dispatch fill land in
``<name>_wait_s`` / ``<name>_batch_fill`` / ``<name>_batch_requests``
histograms (core/metrics.py) — the numbers that prove coalescing happened.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability.devtime import (DEVTIME,
                                                            pow2_bucket)


class _Pending:
    __slots__ = ("items", "event", "result", "error", "enqueued_at")

    def __init__(self, items: Sequence[Any]) -> None:
        self.items = items
        self.event = threading.Event()
        self.result: Optional[Sequence[Any]] = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.perf_counter()


class MicroBatcher:
    """Coalesce concurrent ``submit()`` calls into single dispatches.

    ``dispatch`` takes the concatenated item list and must return one result
    per item, index-aligned (e.g. an ``(n, dim)`` array or a length-n
    sequence) — each caller gets back the contiguous slice covering its own
    items, so results can never leak across requests.
    """

    def __init__(self, dispatch: Callable[[List[Any]], Sequence[Any]],
                 max_items: int = 64, window_s: float = 0.002,
                 max_queue: int = 1024, name: str = "microbatch") -> None:
        self._dispatch = dispatch
        self.max_items = max(1, max_items)
        self.window_s = max(0.0, window_s)
        self.max_queue = max_queue
        self.name = name
        self._queue: List[_Pending] = []
        self._cv = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name=f"{name}-batcher")
        self._worker.start()

    # ------------------------------------------------------------------- API

    def submit(self, items: Sequence[Any]) -> Sequence[Any]:
        """Block until the batch containing ``items`` is dispatched; return
        this submission's results (index-aligned with ``items``)."""
        if not items:
            return []
        pending = _Pending(items)
        with self._cv:
            if self._closed:
                raise RuntimeError(f"{self.name} batcher is closed")
            while len(self._queue) >= self.max_queue and not self._closed:
                # bounded queue: back-pressure the caller instead of letting
                # an ingest burst grow the queue without limit
                self._cv.wait(timeout=0.05)
            if self._closed:
                raise RuntimeError(f"{self.name} batcher is closed")
            self._queue.append(pending)
            self._cv.notify_all()
        pending.event.wait()
        if pending.error is not None:
            raise pending.error
        return pending.result

    def close(self) -> None:
        # Drain the queue UNDER the lock before joining: once popped here
        # the worker can never claim these pendings, so a slow in-flight
        # dispatch cannot race close() into double-completing a submission
        # (the already-popped batch it is working on finishes normally).
        with self._cv:
            self._closed = True
            drained, self._queue = self._queue, []
            self._cv.notify_all()
        for p in drained:
            p.error = RuntimeError(f"{self.name} batcher closed")
            p.event.set()
        self._worker.join(timeout=5)

    # ---------------------------------------------------------------- worker

    def _take_batch(self) -> List[_Pending]:
        """Wait for work, then hold the window open until it expires or the
        batch fills. Returns the drained submissions (possibly exceeding
        max_items by the last submission — never split across dispatches)."""
        with self._cv:
            while not self._queue and not self._closed:
                self._cv.wait(timeout=0.1)
            if not self._queue:
                return []
            deadline = time.perf_counter() + self.window_s
            while (sum(len(p.items) for p in self._queue) < self.max_items):
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._closed:
                    break
                self._cv.wait(timeout=remaining)
            batch: List[_Pending] = []
            count = 0
            while self._queue and (not batch or
                                   count + len(self._queue[0].items)
                                   <= self.max_items):
                p = self._queue.pop(0)
                count += len(p.items)
                batch.append(p)
            self._cv.notify_all()   # wake writers blocked on max_queue
            return batch

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                if self._closed:
                    return
                continue
            now = time.perf_counter()
            flat: List[Any] = []
            for p in batch:
                REGISTRY.histogram(f"{self.name}_wait_s").observe(
                    now - p.enqueued_at)
                flat.extend(p.items)
            REGISTRY.histogram(f"{self.name}_batch_fill").observe(len(flat))
            REGISTRY.histogram(f"{self.name}_batch_requests").observe(
                len(batch))
            REGISTRY.counter(f"{self.name}_dispatches").inc()
            t0 = time.perf_counter()
            try:
                results = self._dispatch(flat)
                if len(results) != len(flat):
                    raise RuntimeError(
                        f"{self.name} dispatch returned {len(results)} "
                        f"results for {len(flat)} items")
            except BaseException as exc:   # noqa: BLE001 — routed to callers
                for p in batch:
                    p.error = exc
                    p.event.set()
                continue
            # devtime ledger: the encoder dispatch blocks until results are
            # host-side, so its wall is a pre-measured duration — no fence
            # in any mode. Bucket = the pow2 batch bucket (the compile
            # unit); mfu=False keeps encoder items out of the LLM's
            # model-FLOP gauges.
            b2 = pow2_bucket(len(flat))
            DEVTIME.commit(self.name, f"b{b2}",
                           device_s=time.perf_counter() - t0,
                           tokens=len(flat), padded_tokens=b2, mfu=False)
            start = 0
            for p in batch:
                p.result = results[start:start + len(p.items)]
                start += len(p.items)
                p.event.set()
