"""Encoder services: embeddings + reranking on TPU.

Replace the NeMo Retriever embedding NIM (`nv-embedqa-e5-v5`,
ref docker-compose-nim-ms.yaml:30-56) and reranking NIM
(`nv-rerankqa-mistral-4b-v3`, ref :58-81) with jitted, batch-bucketed
BERT-class encoders servable in-process or over the same `/v1` REST shapes
the reference's clients consume (utils.py:431-440, 458-471).
"""

from generativeaiexamples_tpu.encoders.embedder import Embedder  # noqa: F401
from generativeaiexamples_tpu.encoders.microbatch import MicroBatcher  # noqa: F401
from generativeaiexamples_tpu.encoders.reranker import Reranker  # noqa: F401
