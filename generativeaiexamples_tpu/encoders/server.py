"""REST surface for the encoder services — NIM-shape parity.

Endpoints match what the reference's LangChain clients call:
  * POST /v1/embeddings — OpenAI embeddings shape with the NIM `input_type`
    extension (query|passage) the embedding NIM exposes
    (ref: utils.py:431-440; docker-compose-nim-ms.yaml:30-56, port 9080)
  * POST /v1/ranking — rerank NIM shape {query:{text}, passages:[{text}]}
    → {rankings:[{index, logit}]} (ref: utils.py:458-466; compose :58-81)
  * GET /health — compose healthcheck parity
"""

from __future__ import annotations

import asyncio
import json
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from aiohttp import web

from generativeaiexamples_tpu.encoders.embedder import Embedder
from generativeaiexamples_tpu.encoders.reranker import Reranker
from generativeaiexamples_tpu.server.common import (
    add_debug_routes, health_handler, metrics_handler)


class EncoderServer:
    def __init__(self, embedder: Optional[Embedder] = None,
                 reranker: Optional[Reranker] = None,
                 model_name: str = "e5-base-tpu",
                 rerank_model_name: str = "rerank-tpu") -> None:
        self.embedder = embedder
        self.reranker = reranker
        self.model_name = model_name
        self.rerank_model_name = rerank_model_name
        # Encoder calls leave the event loop: run inline they would BLOCK
        # it, serializing concurrent HTTP requests and defeating the
        # micro-batcher (encoders/microbatch.py) — with a pool, concurrent
        # requests submit concurrently and coalesce into shared dispatches.
        self._pool = ThreadPoolExecutor(max_workers=16,
                                        thread_name_prefix="encoder-http")
        self.app = web.Application()
        self.app.on_cleanup.append(self._shutdown)
        # shared handlers (server/common.py): /metrics content-negotiates
        # JSON vs Prometheus text exposition, same as the other servers
        routes = [web.get("/health", health_handler),
                  web.get("/metrics", metrics_handler)]
        if embedder is not None:
            routes.append(web.post("/v1/embeddings", self.embeddings))
        if reranker is not None:
            routes.append(web.post("/v1/ranking", self.ranking))
        self.app.add_routes(routes)
        add_debug_routes(self.app)

    async def _shutdown(self, app: web.Application) -> None:
        self._pool.shutdown(wait=False)
        for enc in (self.embedder, self.reranker):
            if enc is not None and hasattr(enc, "close"):
                enc.close()

    async def embeddings(self, request: web.Request) -> web.Response:
        body = await request.json()
        texts = body.get("input", [])
        if isinstance(texts, str):
            texts = [texts]
        if not texts:
            raise web.HTTPBadRequest(text=json.dumps({"error": "empty input"}))
        input_type = body.get("input_type", "passage")
        fn = (self.embedder.embed_queries if input_type == "query"
              else self.embedder.embed_documents)
        vecs = await asyncio.get_running_loop().run_in_executor(
            self._pool, fn, texts)
        return web.json_response({
            "object": "list",
            "model": self.model_name,
            "data": [{"object": "embedding", "index": i, "embedding": v.tolist()}
                     for i, v in enumerate(vecs)],
            "usage": {"prompt_tokens": 0, "total_tokens": 0},
        })

    async def ranking(self, request: web.Request) -> web.Response:
        body = await request.json()
        query = (body.get("query") or {}).get("text", "")
        passages = [p.get("text", "") for p in body.get("passages", [])]
        if not query or not passages:
            raise web.HTTPBadRequest(text=json.dumps(
                {"error": "query and passages required"}))
        top_n = int(body.get("top_n") or len(passages))
        ranked = await asyncio.get_running_loop().run_in_executor(
            self._pool, lambda: self.reranker.rerank(query, passages,
                                                     top_n=top_n))
        return web.json_response({
            "model": self.rerank_model_name,
            "rankings": [{"index": i, "logit": s} for i, s in ranked],
        })


def run_server(embedder: Optional[Embedder] = None,
               reranker: Optional[Reranker] = None,
               host: str = "0.0.0.0", port: int = 9080) -> None:
    from generativeaiexamples_tpu.observability.bootstrap import (
        init_observability)
    init_observability("encoder")
    server = EncoderServer(embedder, reranker)
    web.run_app(server.app, host=host, port=port, print=None)
