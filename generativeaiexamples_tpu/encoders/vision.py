"""Vision encoder service: image embeddings, zero-shot captioning, and
text↔image search on the joint CLIP space.

The in-tree counterpart of the reference's hosted vision stack (ref:
vision_workflows/README.md — NVCLIP multimodal search, NV-DINOv2 few-shot;
RAG/examples/advanced_rag/multimodal_rag's served VLM). Components:

  * :class:`ImageEmbedder` — jitted, batch-bucketed CLIP towers. Loads a
    HuggingFace `CLIPModel` checkpoint from ``APP_VISION_CHECKPOINT_DIR``
    (torch CPU → `models.clip.params_from_hf`); random init serves tests,
    mirroring encoders/embedder.py.
  * :class:`ClipCaptioner` — zero-shot captioning: candidate captions are
    scored by the text tower against the image embedding and the best
    (above a margin) is combined with structural image stats. A real vision
    model behind chains.multimodal's `ImageDescriber` seam.
  * :class:`MultimodalIndex` — image vectors in the device-resident
    retrieval store, queried by text through the joint space (the NVCLIP
    multimodal-search workflow shape).

Preprocessing (resize to the tower's square input + CLIP mean/std
normalization) runs in numpy/PIL on the host — decode is IO, the towers are
the TPU work.
"""

from __future__ import annotations

import io
import logging
import os
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.models import clip
from generativeaiexamples_tpu.retrieval.store import Document, VectorStore

logger = logging.getLogger(__name__)

# CLIP pixel normalization constants (openai/clip-vit family)
_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)

_DEFAULT_CAPTIONS = (
    "a photo", "a chart or graph", "a diagram", "a table of data",
    "a screenshot of a document", "a logo", "a map", "a drawing",
    "a photo of people", "a photo of a landscape", "a photo of an object",
    "text on a plain background",
)


def _decode_image(image_bytes: bytes, size: int) -> Optional[np.ndarray]:
    """bytes → (size, size, 3) float32 in [0,1], or None if undecodable."""
    try:
        from PIL import Image

        img = Image.open(io.BytesIO(image_bytes)).convert("RGB")
        img = img.resize((size, size), Image.BICUBIC)
        return np.asarray(img, np.float32) / 255.0
    except Exception as exc:
        logger.debug("image decode failed (%d bytes): %s",
                     len(image_bytes), exc)
        return None


def _bucket(n: int) -> int:
    """Smallest power of two >= n (one XLA compile per bucket, not per N)."""
    b = 1
    while b < n:
        b *= 2
    return b


class ImageEmbedder:
    """Batched CLIP towers with jit-per-bucket compilation."""

    def __init__(self, cfg: Optional[clip.ClipConfig] = None,
                 params: Optional[clip.Params] = None,
                 checkpoint_dir: str = "") -> None:
        checkpoint_dir = checkpoint_dir or os.environ.get(
            "APP_VISION_CHECKPOINT_DIR", "")
        self._hf_tokenizer = None
        if params is None and checkpoint_dir:
            cfg, params, self._hf_tokenizer = _load_hf_checkpoint(
                checkpoint_dir, cfg)
        self.cfg = cfg or clip.ClipConfig.vit_b32()
        if params is None:
            logger.warning("no vision checkpoint — using RANDOM weights "
                           "(set APP_VISION_CHECKPOINT_DIR for real ones)")
            params = clip.init_params(jax.random.PRNGKey(17), self.cfg)
        self.params = params
        self._img_fn = jax.jit(partial(clip.encode_image, cfg=self.cfg))
        self._txt_fn = jax.jit(partial(clip.encode_text, cfg=self.cfg))

    @property
    def dim(self) -> int:
        return self.cfg.projection_dim

    # ------------------------------------------------------------- images

    def embed_images(self, images: Sequence[bytes]) -> np.ndarray:
        """L2-normalized joint-space vectors (N, dim); undecodable images
        embed to zero vectors (never retrieved)."""
        size = self.cfg.image_size
        if not images:
            return np.zeros((0, self.dim), np.float32)
        pixels, ok = [], []
        for b in images:
            arr = _decode_image(b, size)
            ok.append(arr is not None)
            pixels.append(arr if arr is not None
                          else np.zeros((size, size, 3), np.float32))
        n = len(pixels)
        pad = _bucket(n) - n
        pixels += [np.zeros((size, size, 3), np.float32)] * pad
        batch = (np.stack(pixels) - _MEAN) / _STD
        emb = np.array(self._img_fn(self.params,
                                    pixels=jnp.asarray(batch)))[:n]
        emb /= np.maximum(np.linalg.norm(emb, axis=-1, keepdims=True), 1e-9)
        emb[~np.asarray(ok)] = 0.0
        return emb

    # -------------------------------------------------------------- texts

    def _tokenize(self, texts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Token ids + eos positions for the text tower.

        With a HF checkpoint, the checkpoint's own BPE tokenizer is used
        (trained weights are meaningless on any other vocabulary). The
        byte-level fallback serves random-weight (test) towers only, where
        the requirement is merely deterministic, consistent ids.
        """
        S = self.cfg.max_text_len
        toks = np.zeros((len(texts), S), np.int32)
        eos = np.zeros((len(texts),), np.int32)
        if self._hf_tokenizer is not None:
            enc = self._hf_tokenizer(list(texts), padding="max_length",
                                     truncation=True, max_length=S)
            ids = np.asarray(enc["input_ids"], np.int32)
            toks[:, :ids.shape[1]] = ids
            eos_id = self._hf_tokenizer.eos_token_id
            for i in range(len(texts)):
                hits = np.nonzero(ids[i] == eos_id)[0]
                eos[i] = int(hits[0]) if hits.size else ids.shape[1] - 1
            return toks, eos
        for i, text in enumerate(texts):
            ids = list(text.encode("utf-8"))[: S - 2]
            row = [self.cfg.vocab_size - 2] + \
                [b % (self.cfg.vocab_size - 4) for b in ids] + \
                [self.cfg.vocab_size - 1]
            toks[i, :len(row)] = row
            eos[i] = len(row) - 1
        return toks, eos

    def embed_texts(self, texts: Sequence[str]) -> np.ndarray:
        toks, eos = self._tokenize(texts)
        n = len(texts)
        pad = _bucket(n) - n
        if pad:
            toks = np.concatenate([toks, np.zeros((pad, toks.shape[1]),
                                                  np.int32)])
            eos = np.concatenate([eos, np.zeros((pad,), np.int32)])
        emb = np.asarray(self._txt_fn(self.params, tokens=jnp.asarray(toks),
                                      eos_positions=jnp.asarray(eos)))[:n]
        return emb / np.maximum(np.linalg.norm(emb, axis=-1, keepdims=True),
                                1e-9)


def _load_hf_checkpoint(path: str, cfg: Optional[clip.ClipConfig]):
    """Load a local HF CLIP checkpoint directory (torch CPU) + its BPE."""
    from transformers import AutoTokenizer, CLIPConfig as HFClipConfig, CLIPModel

    hf_cfg = HFClipConfig.from_pretrained(path)
    cfg = cfg or clip.ClipConfig(
        image_size=hf_cfg.vision_config.image_size,
        patch_size=hf_cfg.vision_config.patch_size,
        vision_dim=hf_cfg.vision_config.hidden_size,
        vision_layers=hf_cfg.vision_config.num_hidden_layers,
        vision_heads=hf_cfg.vision_config.num_attention_heads,
        vocab_size=hf_cfg.text_config.vocab_size,
        max_text_len=hf_cfg.text_config.max_position_embeddings,
        text_dim=hf_cfg.text_config.hidden_size,
        text_layers=hf_cfg.text_config.num_hidden_layers,
        text_heads=hf_cfg.text_config.num_attention_heads,
        projection_dim=hf_cfg.projection_dim)
    model = CLIPModel.from_pretrained(path)
    try:
        tokenizer = AutoTokenizer.from_pretrained(path)
    except Exception:
        logger.warning("checkpoint %s has no tokenizer files — text-tower "
                       "queries will use the byte fallback and be "
                       "semantically meaningless on trained weights", path)
        tokenizer = None
    return cfg, clip.params_from_hf(model.state_dict(), cfg), tokenizer


class ClipCaptioner:
    """Zero-shot image captioning via joint-space scoring.

    Candidate captions (a configurable bank) are ranked against the image
    embedding; the winner is merged with structural stats (dimensions,
    source) into the caption the multimodal chain embeds. This is the
    in-tree `ImageDescriber` backed by an actual vision model —
    the reference defers to a served VLM (ref multimodal_rag
    llm/llm_client.py:48 multimodal_invoke).
    """

    def __init__(self, embedder: Optional[ImageEmbedder] = None,
                 captions: Sequence[str] = _DEFAULT_CAPTIONS) -> None:
        self.embedder = embedder or ImageEmbedder()
        self.captions = list(captions)
        self._caption_emb = self.embedder.embed_texts(self.captions)

    def describe(self, image_bytes: bytes, metadata: Dict[str, str]) -> str:
        from generativeaiexamples_tpu.chains.multimodal_parsers import (
            image_summary)

        emb = self.embedder.embed_images([image_bytes])[0]
        stats = image_summary(image_bytes) or "undecodable image"
        src = metadata.get("source", "unknown")
        if not emb.any():
            return f"Image from {src}: {stats}"
        scores = self._caption_emb @ emb
        best = int(np.argmax(scores))
        return (f"Image from {src}: {self.captions[best]} "
                f"(clip score {float(scores[best]):.3f}); {stats}")


class MultimodalIndex:
    """Text→image search over the joint space (NVCLIP-workflow shape):
    images land in the device-resident VectorStore as joint-space vectors;
    queries embed through the text tower."""

    def __init__(self, embedder: Optional[ImageEmbedder] = None) -> None:
        self.embedder = embedder or ImageEmbedder()
        self.store = VectorStore(dim=self.embedder.dim)

    def add_images(self, images: Sequence[bytes],
                   metadatas: Sequence[Dict[str, str]]) -> int:
        emb = self.embedder.embed_images(images)
        keep = [i for i in range(len(images)) if emb[i].any()]
        docs = [Document(content=str(metadatas[i].get("caption", "")),
                         metadata=dict(metadatas[i])) for i in keep]
        if docs:
            self.store.add(docs, emb[keep])
        return len(docs)

    def search(self, query: str, top_k: int = 4,
               score_threshold: float = 0.0) -> List[Tuple[Document, float]]:
        qvec = self.embedder.embed_texts([query])[0]
        return self.store.search(qvec, top_k=top_k,
                                 score_threshold=score_threshold)


class FewShotClassifier:
    """Few-shot image classification over the vision tower's embedding
    space (parity: the NV-DINOv2 few-shot workflow, ref
    vision_workflows/README.md:39-41 — label a handful of examples per
    class, classify by embedding similarity; no training loop).

    Prototype mode averages each class's (normalized) example embeddings —
    one matmul per batch of queries against the class matrix, so the whole
    classifier is a single TPU GEMM. A kNN mode keeps every example for
    irregular class shapes.
    """

    def __init__(self, embedder: Optional[ImageEmbedder] = None,
                 mode: str = "prototype", k: int = 5) -> None:
        if mode not in ("prototype", "knn"):
            raise ValueError(f"unknown mode {mode!r}")
        self.embedder = embedder or ImageEmbedder()
        self.mode = mode
        self.k = k
        self._examples: List[Tuple[str, np.ndarray]] = []
        self._matrix_cache = None   # (labels, matrix[, example labels])

    def add_examples(self, label: str, images: Sequence[bytes]) -> int:
        emb = self.embedder.embed_images(images)
        kept = 0
        for row in emb:
            if row.any():
                self._examples.append((label, row / np.linalg.norm(row)))
                kept += 1
        if kept:
            self._matrix_cache = None
        return kept

    def _matrices(self):
        """Stacked class/example matrices, rebuilt only when examples
        change — classify() stays one GEMM per batch, not a per-request
        Python reduction over the example list."""
        if self._matrix_cache is None:
            labels = self.labels
            if self.mode == "prototype":
                protos = np.stack([
                    np.mean([e for l, e in self._examples if l == lab],
                            axis=0)
                    for lab in labels])
                protos = protos / np.clip(
                    np.linalg.norm(protos, axis=1, keepdims=True), 1e-9,
                    None)
                self._matrix_cache = (labels, protos)
            else:
                ex_mat = np.stack([e for _, e in self._examples])
                ex_lab = [l for l, _ in self._examples]
                self._matrix_cache = (labels, ex_mat, ex_lab)
        return self._matrix_cache

    @property
    def labels(self) -> List[str]:
        return sorted({l for l, _ in self._examples})

    def classify(self, images: Sequence[bytes]
                 ) -> List[Tuple[str, float]]:
        """(label, confidence) per image; confidence is the winning cosine
        (prototype) or the winning class's mean top-k cosine (knn)."""
        if not self._examples:
            raise ValueError("no labeled examples added")
        q = self.embedder.embed_images(images)
        # undecodable images embed to zero; label them "" rather than
        # silently winning the alphabetically-first class at cosine 0
        valid = np.asarray([bool(row.any()) for row in q])
        q = q / np.clip(np.linalg.norm(q, axis=1, keepdims=True), 1e-9, None)
        if self.mode == "prototype":
            labels, protos = self._matrices()
            sims = q @ protos.T                       # (B, n_classes)
            best = np.argmax(sims, axis=1)
            return [(labels[b], float(sims[i, b])) if valid[i] else ("", 0.0)
                    for i, b in enumerate(best)]
        labels, ex_mat, ex_lab = self._matrices()
        sims = q @ ex_mat.T                           # (B, n_examples)
        out = []
        for i, row in enumerate(sims):
            if not valid[i]:
                out.append(("", 0.0))
                continue
            scores = {}
            for lab in labels:
                lab_sims = sorted((row[j] for j in range(len(ex_lab))
                                   if ex_lab[j] == lab), reverse=True)
                scores[lab] = float(np.mean(lab_sims[: self.k]))
            best = max(scores, key=scores.get)
            out.append((best, scores[best]))
        return out
