"""VLM streaming alerts — watch a frame stream for user-defined conditions.

Capability parity with the reference's vision alerting workflow
(ref: vision_workflows/README.md — "VLM Alerts: send frames + an alert
prompt to the VLM NIM; it answers whether the alert condition is present,
and transitions fire notifications"; community variants stream RTSP into
the same loop).

TPU-first mechanics: per-frame yes/no VLM chat would waste the chip on
1-image batches, so the default detector scores frames with the CLIP
towers — alert condition vs. its negation as a zero-shot text pair, one
batched GEMM for a whole window of frames — and only ESCALATES frames
that cross the trigger to the (expensive) VLM captioner for the alert
message. Hysteresis + cooldown turn per-frame scores into clean events:
an alert fires on sustained presence, clears on sustained absence, and
cannot machine-gun notifications.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class AlertRule:
    """One watched condition, phrased as the positive/negative text pair
    CLIP scores against (the zero-shot trick the NV-CLIP workflow uses)."""
    name: str
    condition: str                  # e.g. "a fire is burning"
    negation: str = ""              # default: "no {condition}"
    threshold: float = 0.6          # P(condition) to count a frame as hot
    trigger_frames: int = 2         # consecutive hot frames to raise
    clear_frames: int = 4           # consecutive cold frames to clear
    cooldown_s: float = 10.0        # min seconds between raises

    def __post_init__(self) -> None:
        if not self.negation:
            self.negation = f"no {self.condition}"


@dataclasses.dataclass
class AlertEvent:
    rule: str
    kind: str                       # "raised" | "cleared"
    frame_index: int
    score: float
    message: str = ""
    at: float = 0.0


class _RuleState:
    def __init__(self) -> None:
        self.active = False
        self.hot = 0
        self.cold = 0
        self.last_raise = -1e18


class AlertMonitor:
    """Scores frames against every rule in one batched pass and emits
    raise/clear events with hysteresis."""

    def __init__(self, rules: Sequence[AlertRule], embedder=None,
                 describe: Optional[Callable[[bytes, str], str]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        from generativeaiexamples_tpu.encoders.vision import ImageEmbedder

        if not rules:
            raise ValueError("AlertMonitor needs at least one rule")
        self.rules = list(rules)
        self.embedder = embedder if embedder is not None else ImageEmbedder()
        # escalation hook: alert frame -> human-readable message (a VLM
        # captioner; optional because raising alone is the core workflow)
        self.describe = describe
        self.clock = clock
        self._states = {r.name: _RuleState() for r in self.rules}
        texts = [t for r in self.rules for t in (r.condition, r.negation)]
        tvecs = np.asarray(self.embedder.embed_texts(texts))
        self._pos = tvecs[0::2]               # (R, D)
        self._neg = tvecs[1::2]
        self._frame_index = 0

    # ----------------------------------------------------------- scoring

    def score_frames(self, frames: Sequence[bytes]) -> np.ndarray:
        """(F, R) P(condition) per frame per rule: softmax over the
        condition/negation pair of CLIP logits — one GEMM per window."""
        ivecs = np.asarray(self.embedder.embed_images(frames))   # (F, D)
        pos = ivecs @ self._pos.T                                # (F, R)
        neg = ivecs @ self._neg.T
        # CLIP-style temperature sharpens the pairwise softmax
        scale = 100.0
        return 1.0 / (1.0 + np.exp(-scale * (pos - neg) / 2.0))

    # ------------------------------------------------------------ events

    def process(self, frames: Sequence[bytes]) -> List[AlertEvent]:
        """Feed a window of frames; returns the events they caused."""
        if not frames:
            return []
        scores = self.score_frames(frames)
        events: List[AlertEvent] = []
        for f, frame in enumerate(frames):
            idx = self._frame_index
            self._frame_index += 1
            now = self.clock()
            for r, rule in enumerate(self.rules):
                st = self._states[rule.name]
                p = float(scores[f, r])
                if p >= rule.threshold:
                    st.hot += 1
                    st.cold = 0
                else:
                    st.cold += 1
                    st.hot = 0
                if (not st.active and st.hot >= rule.trigger_frames
                        and now - st.last_raise >= rule.cooldown_s):
                    st.active = True
                    st.last_raise = now
                    message = ""
                    if self.describe is not None:
                        try:
                            message = self.describe(frame, rule.condition)
                        except Exception:
                            logger.exception("alert describe failed")
                    events.append(AlertEvent(rule=rule.name, kind="raised",
                                             frame_index=idx, score=p,
                                             message=message, at=now))
                elif st.active and st.cold >= rule.clear_frames:
                    st.active = False
                    events.append(AlertEvent(rule=rule.name, kind="cleared",
                                             frame_index=idx, score=p,
                                             at=now))
        return events

    def watch(self, stream: Iterable[Sequence[bytes]]
              ) -> Iterator[AlertEvent]:
        """Drive an iterator of frame windows (e.g. a video tap yielding a
        window per second) and yield events as they fire."""
        for window in stream:
            yield from self.process(window)
