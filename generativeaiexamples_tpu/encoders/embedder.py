"""Bi-encoder embedding service (e5-class) — replaces the embedding NIM.

Reference behavior being matched: passage/query embedding with instruction
prefixes ("query: " / "passage: ", the e5 convention), batched over HTTP
(ref client: NVIDIAEmbeddings in utils.py:407-446; `encode_queries` /
`encode_documents` split in multimodal retriever/embedder.py:40).

TPU design: one jitted program per (batch, length) bucket — texts are packed
into power-of-two buckets so every shape compiles once; bf16 matmuls, f32
pooled output, L2-normalized on device. Batch work rides the MXU: at e5-base
scale a v5e chip embeds tens of thousands of passages/s, which is what makes
in-proc ingestion (SURVEY §3.3) faster than the reference's HTTP hop to a
separate GPU container.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.encoders.microbatch import MicroBatcher
from generativeaiexamples_tpu.engine.tokenizer import Tokenizer, get_tokenizer
from generativeaiexamples_tpu.models import bert

QUERY_PREFIX = "query: "
PASSAGE_PREFIX = "passage: "


class Embedder:
    def __init__(self, cfg: Optional[bert.BertConfig] = None,
                 params: Optional[bert.Params] = None,
                 tokenizer: Optional[Tokenizer] = None,
                 max_len: int = 512, max_batch: int = 32,
                 micro_window_s: float = 0.0) -> None:
        self.cfg = cfg or bert.BertConfig.tiny()
        self.params = params if params is not None else bert.init_params(
            jax.random.PRNGKey(11), self.cfg)
        self.tokenizer = tokenizer or get_tokenizer("")
        self.max_len = min(max_len, self.cfg.max_positions)
        self.max_batch = max_batch
        self._embed = jax.jit(
            lambda p, t, m: bert.embed(p, self.cfg, t, m, normalize=True))
        # cross-request micro-batching (encoders/microbatch.py): concurrent
        # embed calls from chains / the HTTP server coalesce into single
        # TPU dispatches. Opt-in — direct bulk users (ingest pipelines,
        # tests) keep the plain path.
        self._batcher: Optional[MicroBatcher] = (
            MicroBatcher(self._run, max_items=max_batch,
                         window_s=micro_window_s, name="embed")
            if micro_window_s > 0 else None)

    @property
    def dim(self) -> int:
        return self.cfg.dim

    def close(self) -> None:
        """Stop the micro-batch worker thread (no-op without one). Code
        that constructs embedders repeatedly in one process must close them
        or leak a parked daemon thread per instance."""
        if self._batcher is not None:
            self._batcher.close()
            self._batcher = None

    def _bucket(self, n: int, cap: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return min(b, cap)

    def _batchify(self, texts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        ids = [self.tokenizer.encode(t)[: self.max_len] for t in texts]
        S = self._bucket(max((len(i) for i in ids), default=1), self.max_len)
        B = self._bucket(len(ids), self.max_batch)
        tokens = np.zeros((B, S), np.int32)
        mask = np.zeros((B, S), bool)
        for r, seq in enumerate(ids):
            tokens[r, :len(seq)] = seq
            mask[r, :len(seq)] = True
        # padding rows keep one valid token so masked-mean never divides by 0
        for r in range(len(ids), B):
            mask[r, 0] = True
        return tokens, mask

    def _run(self, texts: Sequence[str]) -> np.ndarray:
        # dispatch-ahead: issue every batch's program before fetching any
        # result — device compute and the (serialized, ~100 ms each on a
        # remote-attached chip) device→host transfers overlap instead of
        # alternating
        pending = []
        for i in range(0, len(texts), self.max_batch):
            chunk = texts[i:i + self.max_batch]
            tokens, mask = self._batchify(chunk)
            vecs = self._embed(self.params, jnp.asarray(tokens),
                               jnp.asarray(mask))
            pending.append((vecs, len(chunk)))
        # count AFTER the fetch: a failed batch must not report embeddings
        out = []
        for v, n in pending:
            out.append(np.asarray(v)[:n])
            REGISTRY.counter("embeddings_computed").inc(n)
        return (np.concatenate(out, axis=0) if out
                else np.zeros((0, self.dim), np.float32))

    def _dispatch(self, texts: Sequence[str]) -> np.ndarray:
        if not texts:
            return np.zeros((0, self.dim), np.float32)
        if self._batcher is not None:
            # rows route back as this submission's contiguous slice of the
            # coalesced batch output — stack preserves input order
            return np.asarray(self._batcher.submit(list(texts)))
        return self._run(texts)

    def embed_queries(self, texts: Sequence[str]) -> np.ndarray:
        return self._dispatch([QUERY_PREFIX + t for t in texts])

    def embed_documents(self, texts: Sequence[str]) -> np.ndarray:
        return self._dispatch([PASSAGE_PREFIX + t for t in texts])
