"""Cross-encoder reranker — replaces the reranking NIM.

Reference behavior: `NVIDIARerank.compress_documents(query, docs)` scores
(query, passage) pairs with a cross-encoder and keeps top_n — the 40→4
funnel of the multi-turn example (ref: advanced_rag/multi_turn_rag/
chains.py:146-190; client utils.py:448-471; NIM compose :58-81).

TPU design addressing SURVEY §7 hard-part #5 (rerank is O(k) full forwards
per query): all k pairs are packed into ONE bucketed batch and scored in a
single jitted forward — the MXU eats the batch dimension, so the funnel
costs about one forward, not 40.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.encoders.microbatch import MicroBatcher
from generativeaiexamples_tpu.engine.tokenizer import Tokenizer, get_tokenizer
from generativeaiexamples_tpu.models import bert


class Reranker:
    def __init__(self, cfg: Optional[bert.BertConfig] = None,
                 params: Optional[bert.Params] = None,
                 tokenizer: Optional[Tokenizer] = None,
                 max_len: int = 512, max_batch: int = 64,
                 micro_window_s: float = 0.0) -> None:
        self.cfg = cfg or bert.BertConfig.tiny()
        self.params = params if params is not None else bert.init_params(
            jax.random.PRNGKey(13), self.cfg, with_rank_head=True)
        self.tokenizer = tokenizer or get_tokenizer("")
        self.max_len = min(max_len, self.cfg.max_positions)
        self.max_batch = max_batch
        self._score = jax.jit(
            lambda p, t, m, tt: bert.rank_score(p, self.cfg, t, m, tt))
        # cross-request micro-batching: scoring is (query, passage) PAIR
        # granular, so two concurrent requests' 40-passage funnels coalesce
        # into shared dispatches (encoders/microbatch.py). The coalescing
        # unit must hold SEVERAL funnels (a 40→4 funnel is one ~40-pair
        # submission, and submissions never split) — _score_pairs chunks by
        # max_batch internally with dispatch-ahead, so a large unit costs
        # nothing beyond the window.
        self._batcher: Optional[MicroBatcher] = (
            MicroBatcher(self._score_pairs, max_items=4 * max_batch,
                         window_s=micro_window_s, name="rerank")
            if micro_window_s > 0 else None)

    def close(self) -> None:
        """Stop the micro-batch worker thread (no-op without one) — see
        Embedder.close()."""
        if self._batcher is not None:
            self._batcher.close()
            self._batcher = None

    def _bucket(self, n: int, cap: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return min(b, cap)

    def _pack_pairs(self, pairs: Sequence[Tuple[str, str]]):
        """Bucketed (tokens, mask, types) for a batch of (query, passage)
        pairs — pair-granular so one batch can mix queries (the micro-batch
        coalescing unit)."""
        q_cache: dict = {}
        rows = []
        for query, passage in pairs:
            q_ids = q_cache.get(query)
            if q_ids is None:
                q_ids = self.tokenizer.encode(query)[: self.max_len // 2]
                q_cache[query] = q_ids
            p_ids = self.tokenizer.encode(passage)[
                : self.max_len - len(q_ids) - 1]
            rows.append((q_ids, p_ids))
        S = self._bucket(max(len(q) + len(p) + 1 for q, p in rows), self.max_len)
        B = self._bucket(len(rows), self.max_batch)
        tokens = np.zeros((B, S), np.int32)
        mask = np.zeros((B, S), bool)
        types = np.zeros((B, S), np.int32)
        for r, (q, p) in enumerate(rows):
            seq = list(q) + [0] + list(p)
            tokens[r, :len(seq)] = seq
            mask[r, :len(seq)] = True
            types[r, len(q) + 1:len(seq)] = 1  # passage segment
        for r in range(len(rows), B):
            mask[r, 0] = True
        return tokens, mask, types

    def _score_pairs(self, pairs: Sequence[Tuple[str, str]]) -> np.ndarray:
        """Scores for (query, passage) pairs — one jitted batch per
        ≤max_batch, dispatch-ahead across batches (see embedder._run):
        issue all programs, then fetch — hides the per-batch transfer
        round trip."""
        pending = []
        for i in range(0, len(pairs), self.max_batch):
            chunk = pairs[i:i + self.max_batch]
            tokens, mask, types = self._pack_pairs(chunk)
            scores = self._score(self.params, jnp.asarray(tokens),
                                 jnp.asarray(mask), jnp.asarray(types))
            pending.append((scores, len(chunk)))
        REGISTRY.counter("pairs_reranked").inc(len(pairs))
        return np.concatenate([np.asarray(s_)[:n] for s_, n in pending],
                              axis=0)

    def score(self, query: str, passages: Sequence[str]) -> np.ndarray:
        """Relevance scores (len(passages),) for one query."""
        if not passages:
            return np.zeros((0,), np.float32)
        pairs = [(query, p) for p in passages]
        if self._batcher is not None:
            return np.asarray(self._batcher.submit(pairs))
        return self._score_pairs(pairs)

    def rerank(self, query: str, passages: Sequence[str],
               top_n: int = 4) -> List[Tuple[int, float]]:
        """Top-n (index, score) pairs, best first — the 40→4 funnel."""
        scores = self.score(query, passages)
        order = np.argsort(-scores)[:top_n]
        return [(int(i), float(scores[i])) for i in order]
