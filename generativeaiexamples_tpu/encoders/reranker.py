"""Cross-encoder reranker — replaces the reranking NIM.

Reference behavior: `NVIDIARerank.compress_documents(query, docs)` scores
(query, passage) pairs with a cross-encoder and keeps top_n — the 40→4
funnel of the multi-turn example (ref: advanced_rag/multi_turn_rag/
chains.py:146-190; client utils.py:448-471; NIM compose :58-81).

TPU design addressing SURVEY §7 hard-part #5 (rerank is O(k) full forwards
per query): all k pairs are packed into ONE bucketed batch and scored in a
single jitted forward — the MXU eats the batch dimension, so the funnel
costs about one forward, not 40.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.engine.tokenizer import Tokenizer, get_tokenizer
from generativeaiexamples_tpu.models import bert


class Reranker:
    def __init__(self, cfg: Optional[bert.BertConfig] = None,
                 params: Optional[bert.Params] = None,
                 tokenizer: Optional[Tokenizer] = None,
                 max_len: int = 512, max_batch: int = 64) -> None:
        self.cfg = cfg or bert.BertConfig.tiny()
        self.params = params if params is not None else bert.init_params(
            jax.random.PRNGKey(13), self.cfg, with_rank_head=True)
        self.tokenizer = tokenizer or get_tokenizer("")
        self.max_len = min(max_len, self.cfg.max_positions)
        self.max_batch = max_batch
        self._score = jax.jit(
            lambda p, t, m, tt: bert.rank_score(p, self.cfg, t, m, tt))

    def _bucket(self, n: int, cap: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return min(b, cap)

    def _pack(self, query: str, passages: Sequence[str]):
        q_ids = self.tokenizer.encode(query)[: self.max_len // 2]
        rows = []
        for p in passages:
            p_ids = self.tokenizer.encode(p)[: self.max_len - len(q_ids) - 1]
            rows.append((q_ids, p_ids))
        S = self._bucket(max(len(q) + len(p) + 1 for q, p in rows), self.max_len)
        B = self._bucket(len(rows), self.max_batch)
        tokens = np.zeros((B, S), np.int32)
        mask = np.zeros((B, S), bool)
        types = np.zeros((B, S), np.int32)
        for r, (q, p) in enumerate(rows):
            seq = list(q) + [0] + list(p)
            tokens[r, :len(seq)] = seq
            mask[r, :len(seq)] = True
            types[r, len(q) + 1:len(seq)] = 1  # passage segment
        for r in range(len(rows), B):
            mask[r, 0] = True
        return tokens, mask, types

    def score(self, query: str, passages: Sequence[str]) -> np.ndarray:
        """Relevance scores (len(passages),) — one jitted batch per ≤max_batch."""
        if not passages:
            return np.zeros((0,), np.float32)
        # dispatch-ahead across batches (see embedder._run): issue all
        # programs, then fetch — hides the per-batch transfer round trip
        pending = []
        for i in range(0, len(passages), self.max_batch):
            chunk = passages[i:i + self.max_batch]
            tokens, mask, types = self._pack(query, chunk)
            scores = self._score(self.params, jnp.asarray(tokens),
                                 jnp.asarray(mask), jnp.asarray(types))
            pending.append((scores, len(chunk)))
        REGISTRY.counter("pairs_reranked").inc(len(passages))
        return np.concatenate([np.asarray(s_)[:n] for s_, n in pending],
                              axis=0)

    def rerank(self, query: str, passages: Sequence[str],
               top_n: int = 4) -> List[Tuple[int, float]]:
        """Top-n (index, score) pairs, best first — the 40→4 funnel."""
        scores = self.score(query, passages)
        order = np.argsort(-scores)[:top_n]
        return [(int(i), float(scores[i])) for i in order]
