"""StarCoder2-family configs + HF weight import.

Parity target: the reference's code-model fine-tuning recipes
(ref: finetuning/StarCoder2/{lora,inference}.ipynb — LoRA on StarCoder2 in
a NeMo container, then TRT-LLM export) and the code-LLM serving they imply.
Like Gemma (models/gemma.py), the architecture is expressed as
`models.llama.LlamaConfig` knobs, so serving (paged engine, int8 quant),
LoRA/SFT training, and the mesh sharding rules all work unchanged:

  * ``norm="layernorm"`` — classic LayerNorm with affine bias (not RMSNorm);
  * ``use_bias=True``    — biased q/k/v/o and MLP projections;
  * ``mlp="plain"``      — ungated c_fc → gelu_tanh → c_proj (w_up/w_down);
  * ``sliding_window``   — 4096-token windowed attention (masked in the XLA
    attention paths; the pallas kernels are full-causal and auto-gate off).

Weight import maps HF `Starcoder2ForCausalLM` state dicts (torch, CPU) into
the stacked-layer layout, transposing torch's (out, in) Linears.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.llama import LlamaConfig

Params = Dict[str, Any]


def starcoder2_3b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=49152, dim=3072, n_layers=30, n_heads=24, n_kv_heads=2,
        hidden_dim=12288, head_dim=128, rope_theta=999999.4420358813,
        norm_eps=1e-5, tie_embeddings=True, hidden_act="gelu_tanh",
        norm="layernorm", use_bias=True, mlp="plain", sliding_window=4096)


def starcoder2_7b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=49152, dim=4608, n_layers=32, n_heads=36, n_kv_heads=4,
        hidden_dim=18432, head_dim=128, rope_theta=1e6, norm_eps=1e-5,
        tie_embeddings=True, hidden_act="gelu_tanh", norm="layernorm",
        use_bias=True, mlp="plain", sliding_window=4096)


def tiny(vocab_size: int = 256) -> LlamaConfig:
    """Test-scale StarCoder2-shaped config (fake backend, SURVEY §4)."""
    return LlamaConfig(
        vocab_size=vocab_size, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=128, head_dim=16, rope_theta=10000.0,
        tie_embeddings=True, dtype="float32", hidden_act="gelu_tanh",
        norm="layernorm", use_bias=True, mlp="plain", sliding_window=16)


def params_from_hf(state_dict: Dict[str, Any], cfg: LlamaConfig) -> Params:
    """Map a HF `Starcoder2ForCausalLM.state_dict()` into the stacked
    layout (mirrors llama.params_from_hf; extra bias/norm-bias tensors)."""
    import numpy as np

    def t(name):
        w = state_dict[name]
        arr = w.detach().cpu().numpy() if hasattr(w, "detach") else np.asarray(w)
        return jnp.asarray(arr, cfg.jdtype)

    def lin(name):  # torch Linear: (out, in) -> (in, out)
        return t(name).T

    names = ("attn_norm", "attn_norm_b", "wq", "wq_b", "wk", "wk_b",
             "wv", "wv_b", "wo", "wo_b", "mlp_norm", "mlp_norm_b",
             "w_up", "w_up_b", "w_down", "w_down_b")
    layers: Dict[str, list] = {k: [] for k in names}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        layers["attn_norm"].append(t(p + "input_layernorm.weight"))
        layers["attn_norm_b"].append(t(p + "input_layernorm.bias"))
        layers["wq"].append(lin(p + "self_attn.q_proj.weight"))
        layers["wq_b"].append(t(p + "self_attn.q_proj.bias"))
        layers["wk"].append(lin(p + "self_attn.k_proj.weight"))
        layers["wk_b"].append(t(p + "self_attn.k_proj.bias"))
        layers["wv"].append(lin(p + "self_attn.v_proj.weight"))
        layers["wv_b"].append(t(p + "self_attn.v_proj.bias"))
        layers["wo"].append(lin(p + "self_attn.o_proj.weight"))
        layers["wo_b"].append(t(p + "self_attn.o_proj.bias"))
        layers["mlp_norm"].append(t(p + "post_attention_layernorm.weight"))
        layers["mlp_norm_b"].append(t(p + "post_attention_layernorm.bias"))
        layers["w_up"].append(lin(p + "mlp.c_fc.weight"))
        layers["w_up_b"].append(t(p + "mlp.c_fc.bias"))
        layers["w_down"].append(lin(p + "mlp.c_proj.weight"))
        layers["w_down_b"].append(t(p + "mlp.c_proj.bias"))

    params: Params = {
        "embed": t("model.embed_tokens.weight"),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
        "final_norm": t("model.norm.weight"),
        "final_norm_b": t("model.norm.bias"),
    }
    if not cfg.tie_embeddings:
        key = "lm_head.weight"
        params["lm_head"] = (t(key).T if key in state_dict
                             else t("model.embed_tokens.weight").T)
    return params
