"""Model zoo: functional JAX implementations of the model families the
reference serves through external containers (SURVEY §2.5).

Decoder LMs (replace NIM LLM containers): Llama-3 family (`llama`), Gemma
(`gemma`), StarCoder2 (`starcoder2`) — pure-function forward passes over
parameter pytrees, layers stacked + `lax.scan`-ed for compile time,
logical-axis annotations for mesh sharding.

Encoders (replace NeMo Retriever NIMs): e5-class bi-encoder and cross-encoder
reranker (`bert`), CLIP-style vision tower (`clip`).
"""


def model_configs():
    """Name → config factory for every decoder family (shared by the train
    CLI and the serving engine, so a fine-tuned checkpoint serves under the
    same name it trained under)."""
    from generativeaiexamples_tpu.models import gemma, llama, starcoder2

    return {
        "llama3-8b": llama.LlamaConfig.llama3_8b,
        "llama3-70b": llama.LlamaConfig.llama3_70b,
        "mixtral-8x7b": llama.LlamaConfig.mixtral_8x7b,
        "tiny-moe": llama.LlamaConfig.tiny_moe,
        "gemma-2b": gemma.gemma_2b,
        "gemma-7b": gemma.gemma_7b,
        "codegemma-7b": gemma.codegemma_7b,
        "starcoder2-3b": starcoder2.starcoder2_3b,
        "starcoder2-7b": starcoder2.starcoder2_7b,
        "tiny": llama.LlamaConfig.tiny,
        "tiny-gemma": gemma.tiny,
        "tiny-starcoder2": starcoder2.tiny,
    }
