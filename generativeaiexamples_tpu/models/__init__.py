"""Model zoo: functional JAX implementations of the model families the
reference serves through external containers (SURVEY §2.5).

Decoder LMs (replace NIM LLM containers): Llama-3 family (`llama`), Gemma
(`gemma`), StarCoder2 (`starcoder2`) — pure-function forward passes over
parameter pytrees, layers stacked + `lax.scan`-ed for compile time,
logical-axis annotations for mesh sharding.

Encoders (replace NeMo Retriever NIMs): e5-class bi-encoder and cross-encoder
reranker (`bert`), CLIP-style vision tower (`clip`).
"""
