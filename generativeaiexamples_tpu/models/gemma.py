"""Gemma-family decoder LM — the recipe gallery's second architecture.

The reference fine-tunes Gemma/CodeGemma through NeMo notebooks
(ref: finetuning/Gemma/lora.ipynb, finetuning/Gemma/sft.ipynb,
finetuning/Codegemma/lora.ipynb). Architecturally Gemma-1 is the llama block
with three deltas, all expressible as `models.llama.LlamaConfig` knobs plus
weight-folding at import time — so serving (paged engine), LoRA/SFT training,
sharding rules, and ring attention all work on Gemma with zero new model
code:

  * **GeGLU MLP** — tanh-approx GELU gating (``hidden_act="gelu_tanh"``);
  * **embedding scaling** — hidden states are multiplied by sqrt(dim) after
    the token lookup (``embed_scale``);
  * **RMSNorm offset** — Gemma computes ``x_norm * (1 + w)``; `params_from_hf`
    folds the +1 into the stored weights, so the shared rms_norm applies
    unchanged (random init uses ones, the folded identity).

Gemma always ties embeddings (no lm_head) and allows head_dim * n_heads !=
dim (e.g. 2B: dim 2048, 8 heads of 256), which the llama layout already
supports.

Because every serving path keys off LlamaConfig knobs, Gemma also rides the
mixed-phase dispatch (engine/kv_cache.mixed_step → ops/pallas
ragged_paged_attention) unchanged: ``embed_scale`` applies inside the shared
``embed_tokens`` and the 256-wide heads sit inside the ragged kernel's
head_dim limits, so the engine-init gate resolves exactly as for llama.
"""

from __future__ import annotations

import math
from typing import Any, Dict

import jax.numpy as jnp

from generativeaiexamples_tpu.models import llama
from generativeaiexamples_tpu.models.llama import LlamaConfig

Params = Dict[str, Any]


def gemma_2b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=256000, dim=2048, n_layers=18, n_heads=8, n_kv_heads=1,
        hidden_dim=16384, head_dim=256, rope_theta=10000.0, norm_eps=1e-6,
        tie_embeddings=True, hidden_act="gelu_tanh",
        embed_scale=math.sqrt(2048.0))


def gemma_7b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=256000, dim=3072, n_layers=28, n_heads=16, n_kv_heads=16,
        hidden_dim=24576, head_dim=256, rope_theta=10000.0, norm_eps=1e-6,
        tie_embeddings=True, hidden_act="gelu_tanh",
        embed_scale=math.sqrt(3072.0))


def codegemma_7b() -> LlamaConfig:
    """CodeGemma shares the 7B architecture (code-specialized weights)."""
    return gemma_7b()


def tiny(vocab_size: int = 256) -> LlamaConfig:
    """Deterministic test-scale gemma (SURVEY §4 fake-backend style)."""
    return LlamaConfig(
        vocab_size=vocab_size, dim=64, n_layers=2, n_heads=4, n_kv_heads=1,
        hidden_dim=128, head_dim=16, rope_theta=10000.0, norm_eps=1e-6,
        tie_embeddings=True, hidden_act="gelu_tanh",
        embed_scale=math.sqrt(64.0), dtype="float32")


def params_from_hf(state_dict: Dict[str, Any], cfg: LlamaConfig) -> Params:
    """Map a HF `GemmaForCausalLM.state_dict()` into the llama layout.

    Identical tensor names to llama (q/k/v/o, gate/up/down, norms), so the
    llama importer does the transposes/stacking; the Gemma-specific step is
    folding the RMSNorm ``(1 + w)`` offset into the stored norm weights.
    """
    params = llama.params_from_hf(state_dict, cfg)
    one = jnp.asarray(1.0, params["final_norm"].dtype)
    params["layers"]["attn_norm"] = params["layers"]["attn_norm"] + one
    params["layers"]["mlp_norm"] = params["layers"]["mlp_norm"] + one
    params["final_norm"] = params["final_norm"] + one
    return params
