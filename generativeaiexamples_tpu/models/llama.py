"""Llama-3-family decoder LM — functional JAX, TPU-first.

Replaces the model inside the reference's "NIM for LLMs" container
(ref: RAG/examples/local_deploy/docker-compose-nim-ms.yaml:2-28, serving
meta/llama3-8b-instruct per docs/support-matrix.md:17-19). Architecture:
pre-norm transformer, RMSNorm, RoPE (HF split-half convention), GQA,
SwiGLU MLP.

Design (TPU-first, not a torch translation):
  * params are a plain pytree; per-layer tensors are **stacked** on a leading
    layer axis and the block is applied with `lax.scan` — one compiled block
    regardless of depth (fast XLA compiles, friendly to pipeline sharding);
  * every leaf carries a logical-axis annotation (`logical_axes`) consumed by
    parallel.sharding rules — TP/FSDP are rule-table swaps, the model never
    names a mesh axis;
  * three entry points: `forward` (full-sequence, training/scoring),
    `prefill` (fills a dense KV cache, returns last-position logits), and
    `decode_step` (single-token, cache-indexed) — the continuous-batching
    engine jits the latter two;
  * optional LoRA adapter pytree threaded through the projections
    (train/lora.py builds it), so serving merged or unmerged adapters is the
    same code path.

Weight import: `params_from_hf` maps HuggingFace `LlamaForCausalLM` state
(torch, CPU) into this layout — used by tests for numerical parity and by
deployments with local HF checkpoints.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.ops import pallas as pallas_ops
from generativeaiexamples_tpu.ops import quant
from generativeaiexamples_tpu.ops.attention import mha_decode, mha_prefill
from generativeaiexamples_tpu.ops.layers import (
    activate, apply_rope, glu, layer_norm, rms_norm, rotary_embedding)

Params = Dict[str, Any]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    head_dim: int = 128
    rope_theta: float = 500000.0
    # llama3 rope-scaling rule as (factor, low_freq_factor, high_freq_factor,
    # original_max_position_embeddings); None = plain RoPE. A tuple (not a
    # dict) so the frozen config stays hashable for jit static closures.
    rope_scaling: Optional[Tuple[float, float, float, int]] = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: str = "bfloat16"
    # family knobs: "silu" (llama SwiGLU) | "gelu_tanh" (gemma GeGLU) MLP
    # gating, and an embedding-output multiplier (gemma scales by sqrt(dim))
    hidden_act: str = "silu"
    embed_scale: float = 1.0
    # StarCoder2-family knobs (models/starcoder2.py): LayerNorm with affine
    # bias instead of RMSNorm, biased projections, an ungated c_fc→act→c_proj
    # MLP, and sliding-window attention (0 = full causal)
    norm: str = "rms"        # "rms" | "layernorm"
    use_bias: bool = False
    mlp: str = "glu"         # "glu" | "plain" | "moe" (ops/moe.py)
    sliding_window: int = 0
    # MoE knobs (mlp="moe"): top-k routed GLU experts sharded over the
    # mesh's "expert" axis; aux load-balance loss via forward(return_aux=)
    n_experts: int = 8
    n_experts_per_tok: int = 2
    capacity_factor: float = 1.25
    # "xla" | "pallas": inference attention backend. Pallas kernels
    # (ops/pallas/attention.py: flash prefill, ragged/paged decode, and the
    # mixed-phase ragged-paged kernel that engine/kv_cache.mixed_step fuses
    # prefill chunks + decode rows through) need head-axis-unsharded
    # layouts; callers that shard heads over a tensor axis must keep "xla"
    # (or wrap the kernels in shard_map).
    attn_impl: str = "xla"

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
                           hidden_dim=28672)

    @staticmethod
    def mixtral_8x7b() -> "LlamaConfig":
        """Mixtral-class sparse MoE (top-2 of 8 GLU experts per token);
        serves/trains through the same block — experts shard over the
        "expert" mesh axis (ops/moe.py, parallel ep)."""
        return LlamaConfig(vocab_size=32000, dim=4096, n_layers=32,
                           n_heads=32, n_kv_heads=8, hidden_dim=14336,
                           rope_theta=1e6, mlp="moe", n_experts=8,
                           n_experts_per_tok=2)

    @staticmethod
    def tiny_moe(vocab_size: int = 300) -> "LlamaConfig":
        """Test-scale sparse-MoE config: LlamaConfig.tiny (float32 —
        deterministic greedy tests) with the MLP swapped for top-2-of-4
        routed experts."""
        return replace(LlamaConfig.tiny(vocab_size), mlp="moe",
                       n_experts=4, n_experts_per_tok=2,
                       capacity_factor=2.0)

    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        """Deterministic test-scale config (the 'fake backend' of SURVEY §4)."""
        return LlamaConfig(vocab_size=vocab_size, dim=64, n_layers=2, n_heads=4,
                           n_kv_heads=2, hidden_dim=128, head_dim=16,
                           rope_theta=10000.0, tie_embeddings=True,
                           dtype="float32")

    @property
    def jdtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Random init (serving tests / pretraining). Scaled-normal fan-in init."""
    L, D, H, KV, HD, F = (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                          cfg.head_dim, cfg.hidden_dim)
    keys = jax.random.split(rng, 10)
    dt = cfg.jdtype

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(dt)

    layers = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": normal(keys[1], (L, D, H * HD), D),
        "wk": normal(keys[2], (L, D, KV * HD), D),
        "wv": normal(keys[3], (L, D, KV * HD), D),
        "wo": normal(keys[4], (L, H * HD, D), H * HD),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if cfg.mlp == "moe":
        if cfg.use_bias:
            raise ValueError("mlp='moe' does not support use_bias")
        E = cfg.n_experts
        layers["w_router"] = normal(keys[9], (L, D, E), D)
        layers["w_gate"] = normal(keys[5], (L, E, D, F), D)
        layers["w_up"] = normal(keys[6], (L, E, D, F), D)
        layers["w_down"] = normal(keys[7], (L, E, F, D), F)
    else:
        layers["w_up"] = normal(keys[6], (L, D, F), D)
        layers["w_down"] = normal(keys[7], (L, F, D), F)
        if cfg.mlp == "glu":
            layers["w_gate"] = normal(keys[5], (L, D, F), D)
    if cfg.use_bias:
        for name, width in (("wq", H * HD), ("wk", KV * HD), ("wv", KV * HD),
                            ("wo", D), ("w_up", F), ("w_down", D)):
            layers[name + "_b"] = jnp.zeros((L, width), dt)
        if cfg.mlp == "glu":
            layers["w_gate_b"] = jnp.zeros((L, F), dt)
    if cfg.norm == "layernorm":
        layers["attn_norm_b"] = jnp.zeros((L, D), dt)
        layers["mlp_norm_b"] = jnp.zeros((L, D), dt)
    params: Params = {
        "embed": normal(keys[0], (cfg.vocab_size, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
    }
    if cfg.norm == "layernorm":
        params["final_norm_b"] = jnp.zeros((D,), dt)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(keys[8], (D, cfg.vocab_size), D)
    return params


def logical_axes(cfg: LlamaConfig) -> Params:
    """Logical sharding annotations mirroring `init_params` (layer axis = None)."""
    # The embed table uses distinct logical axes from the unembed: token
    # gather from a vocab-sharded table is ambiguous for the partitioner, so
    # rules keep vocab_table replicated and shard the feature dim instead.
    layers = {
        "attn_norm": (None, "embed"),
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "kv_heads"),
        "wv": (None, "embed", "kv_heads"),
        "wo": (None, "heads", "embed"),
        "mlp_norm": (None, "embed"),
    }
    if cfg.mlp == "moe":
        layers["w_router"] = (None, "embed", None)
        layers["w_gate"] = (None, "expert", "embed", "mlp")
        layers["w_up"] = (None, "expert", "embed", "mlp")
        layers["w_down"] = (None, "expert", "mlp", "embed")
    else:
        layers["w_up"] = (None, "embed", "mlp")
        layers["w_down"] = (None, "mlp", "embed")
        if cfg.mlp == "glu":
            layers["w_gate"] = (None, "embed", "mlp")
    if cfg.use_bias:
        # biases shard with their projection's OUTPUT axis
        layers.update({"wq_b": (None, "heads"), "wk_b": (None, "kv_heads"),
                       "wv_b": (None, "kv_heads"), "wo_b": (None, "embed"),
                       "w_up_b": (None, "mlp"), "w_down_b": (None, "embed")})
        if cfg.mlp == "glu":
            layers["w_gate_b"] = (None, "mlp")
    if cfg.norm == "layernorm":
        layers["attn_norm_b"] = (None, "embed")
        layers["mlp_norm_b"] = (None, "embed")
    ax: Params = {
        "embed": ("vocab_table", "embed_table"),
        "layers": layers,
        "final_norm": ("embed",),
    }
    if cfg.norm == "layernorm":
        ax["final_norm_b"] = ("embed",)
    if not cfg.tie_embeddings:
        ax["lm_head"] = ("embed", "vocab")
    return ax


# ---------------------------------------------------------------------------
# KV cache (dense; the paged variant lives in engine/kv_cache.py)
# ---------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
@dataclass
class KVCache:
    """Dense per-layer KV cache: k,v (L, B, T, n_kv, head_dim); lengths (B,)."""

    k: jnp.ndarray
    v: jnp.ndarray
    lengths: jnp.ndarray

    def tree_flatten(self):
        return (self.k, self.v, self.lengths), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)

    @staticmethod
    def create(cfg: LlamaConfig, batch: int, max_seq: int) -> "KVCache":
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        return KVCache(k=jnp.zeros(shape, cfg.jdtype), v=jnp.zeros(shape, cfg.jdtype),
                       lengths=jnp.zeros((batch,), jnp.int32))


# ---------------------------------------------------------------------------
# Forward passes
# ---------------------------------------------------------------------------

def embed_tokens(params: Params, cfg: LlamaConfig,
                 tokens: jnp.ndarray) -> jnp.ndarray:
    """Token embedding lookup with the family's output scaling."""
    h = quant.take(params["embed"], tokens, cfg.jdtype)
    if cfg.embed_scale != 1.0:
        h = h * jnp.asarray(cfg.embed_scale, h.dtype)
    return h


def _maybe_lora(x: jnp.ndarray, base_out: jnp.ndarray, adapters: Optional[Params],
                name: str, adapter_ix: Optional[jnp.ndarray] = None
                ) -> jnp.ndarray:
    """Add a low-rank update x@A@B·(α/r) if an adapter exists for `name`.

    Adapter layout (built by train/lora.py): adapters[name] = {"a": (r, in),
    "b": (out, r) * already stacked per layer when scanned} with scale folded
    into "b" at build time.

    STACKED serving layout (engine multi-LoRA, per-layer slice ndim 3):
    a (N, in, r), b (N, r, out) — N resident adapter slots, slot 0 all-zero
    (the base model). ``adapter_ix`` (B,) selects each batch row's slot;
    the update runs for every slot then gathers per row (N·r tiny work —
    cheaper than a per-row (in, r) weight gather, and one program serves
    any adapter mix).
    """
    if adapters is None or name not in adapters:
        return base_out
    a = adapters[name]["a"]
    b = adapters[name]["b"]
    if a.ndim == 3:
        B = x.shape[0]
        ix = (adapter_ix.astype(jnp.int32) if adapter_ix is not None
              else jnp.zeros((B,), jnp.int32))
        bi = jnp.arange(B, dtype=jnp.int32)
        za = jnp.einsum("bsi,nir->nbsr", x, a.astype(x.dtype))
        z = za[ix, bi]                                    # (B, S, r)
        # the second projection selects FIRST: the slot is known by now,
        # so gather b[ix] (B·r·out elements — small) instead of running
        # all N slots' projections
        zo = jnp.einsum("bsr,bro->bso", z, b.astype(x.dtype)[ix])
        return base_out + zo
    return base_out + (x @ a.astype(x.dtype)) @ b.astype(x.dtype)


def _norm(cfg: LlamaConfig, x: jnp.ndarray, layer: Params,
          name: str) -> jnp.ndarray:
    if cfg.norm == "layernorm":
        return layer_norm(x, layer[name], layer[name + "_b"], cfg.norm_eps)
    return rms_norm(x, layer[name], cfg.norm_eps)


def _proj(cfg: LlamaConfig, x: jnp.ndarray, layer: Params, name: str,
          adapters: Optional[Params],
          adapter_ix: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """x @ W (+ b) with the quant seam and optional LoRA update."""
    y = quant.matmul(x, layer[name])
    if cfg.use_bias:
        y = y + layer[name + "_b"].astype(y.dtype)
    return _maybe_lora(x, y, adapters, name, adapter_ix)


def _block(cfg: LlamaConfig, h: jnp.ndarray, layer: Params,
           cos: jnp.ndarray, sin: jnp.ndarray,
           attn_fn, adapters: Optional[Params],
           adapter_ix: Optional[jnp.ndarray] = None
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One transformer block; `attn_fn(q, k, v) -> ctx` abstracts prefill vs
    decode vs paged attention so the same block serves all paths. Returns
    (h, aux): aux is the MoE load-balance loss contribution (0 for dense
    MLPs), summed across layers by the scan carriers."""
    B, S, D = h.shape
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    x = _norm(cfg, h, layer, "attn_norm")
    q = _proj(cfg, x, layer, "wq", adapters, adapter_ix).reshape(B, S, H, HD)
    k = _proj(cfg, x, layer, "wk", adapters, adapter_ix).reshape(B, S, KV, HD)
    v = _proj(cfg, x, layer, "wv", adapters, adapter_ix).reshape(B, S, KV, HD)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    ctx = attn_fn(q, k, v).reshape(B, S, H * HD)
    h = h + _proj(cfg, ctx, layer, "wo", adapters, adapter_ix)

    x = _norm(cfg, h, layer, "mlp_norm")
    aux = jnp.float32(0.0)
    if cfg.mlp == "moe":
        from generativeaiexamples_tpu.ops.moe import moe_mlp

        moe_out, aux = moe_mlp(
            {k_: layer[k_] for k_ in ("w_router", "w_gate", "w_up",
                                      "w_down")},
            x, k=cfg.n_experts_per_tok,
            capacity_factor=cfg.capacity_factor,
            hidden_act=cfg.hidden_act)
        return h + moe_out, aux
    if cfg.mlp == "glu":
        gate = _proj(cfg, x, layer, "w_gate", adapters, adapter_ix)
        up = _proj(cfg, x, layer, "w_up", adapters, adapter_ix)
        act = glu(gate, up, cfg.hidden_act)
    else:   # plain c_fc -> act -> c_proj (StarCoder2)
        act = activate(_proj(cfg, x, layer, "w_up", adapters, adapter_ix), cfg.hidden_act)
    return h + _proj(cfg, act, layer, "w_down", adapters, adapter_ix), aux


def _unembed(cfg: LlamaConfig, params: Params, h: jnp.ndarray) -> jnp.ndarray:
    h = _norm(cfg, h, params, "final_norm")
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    if isinstance(head, quant.QTensor):
        return quant.matmul(h, head).astype(jnp.float32)
    return (h @ head.astype(h.dtype)).astype(jnp.float32)


REMAT_POLICIES = {
    # save matmul outputs, recompute elementwise in backward: ~zero extra
    # FLOPs, cuts per-layer residual memory enough to double the trainable
    # microbatch on one chip
    "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    # recompute everything (max memory savings, +1 forward of FLOPs)
    "full": jax.checkpoint_policies.nothing_saveable,
}


def forward(params: Params, cfg: LlamaConfig, tokens: jnp.ndarray,
            positions: Optional[jnp.ndarray] = None,
            attn_mask: Optional[jnp.ndarray] = None,
            adapters: Optional[Params] = None,
            attn_fn=None, return_aux: bool = False,
            input_embeds: Optional[jnp.ndarray] = None,
            remat: Optional[str] = None):
    """Full-sequence causal LM: tokens (B, S) → logits (B, S, vocab) f32.

    ``input_embeds`` (B, S, D) replaces the token-embedding lookup — the
    VLM path (models/vlm.py) splices image patch features into the
    sequence before calling in; ``tokens`` still supplies shapes/positions.

    Training/scoring path (no cache). `attn_mask` (B, S) marks valid tokens
    for right-padded batches. ``attn_fn(q, k, v) -> ctx`` overrides the
    attention implementation (e.g. sequence-parallel ring attention); the
    default is full-sequence `mha_prefill`. ``return_aux=True`` additionally
    returns the layer-mean MoE load-balance loss (0 for dense models) —
    the trainer adds it to the LM loss. ``remat`` selects a rematerial-
    ization policy (REMAT_POLICIES key) for the layer scan — a no-op for
    inference-only use; under grad it trades recompute for activation
    memory (jax.checkpoint).
    """
    B, S = tokens.shape
    if attn_fn is not None and attn_mask is not None:
        raise ValueError(
            "attn_mask is ignored when attn_fn is supplied — encode padding "
            "into attn_fn (e.g. sequence_parallel_attention's kv_lens)")
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    h = (input_embeds if input_embeds is not None
         else embed_tokens(params, cfg, tokens))
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)

    attn = attn_fn if attn_fn is not None else partial(
        mha_prefill, q_positions=positions, kv_positions=positions,
        kv_mask=attn_mask, causal=True, window=cfg.sliding_window)

    def body(carry, xs):
        h, aux = carry
        layer, ad = xs
        h, layer_aux = _block(cfg, h, layer, cos, sin, attn, ad)
        return (h, aux + layer_aux), None

    if remat is not None:
        body = jax.checkpoint(body, policy=REMAT_POLICIES[remat])
    # {} is a leafless pytree: scan carries it through unchanged, and
    # _maybe_lora sees an empty adapter dict — one code path either way.
    (h, aux), _ = jax.lax.scan(
        body, (h, jnp.float32(0.0)), (params["layers"], adapters or {}))
    logits = _unembed(cfg, params, h)
    if return_aux:
        return logits, aux / cfg.n_layers
    return logits


def forward_seq_parallel(params: Params, cfg: LlamaConfig, tokens: jnp.ndarray,
                         mesh, attn_mask: Optional[jnp.ndarray] = None,
                         adapters: Optional[Params] = None,
                         impl: str = "ring") -> jnp.ndarray:
    """Long-context full-sequence forward, sequence-sharded over mesh["seq"].

    Same math as :func:`forward`, but attention runs as ring attention (or
    Ulysses all-to-all) via `parallel.ring_attention`, with activations laid
    out (B, S/"seq", ...) so a context that would blow single-chip HBM is
    spread over the ICI ring. Everything outside attention is pointwise in
    the sequence dim, so XLA keeps the "seq" sharding end to end; callers
    place ``tokens`` with P(("data" if present), "seq") and params per
    LONG_CONTEXT_RULES. This is the §5.7 capability the reference lacks
    (its long-context story is trimming retrieval to 1,500 tokens,
    ref utils.py:103).
    """
    from generativeaiexamples_tpu.parallel.ring_attention import (
        sequence_parallel_attention)

    if cfg.sliding_window:
        raise NotImplementedError(
            "sequence-parallel attention is full-causal; sliding-window "
            "models use the chunked-prefill path instead")
    B, S = tokens.shape
    kv_lens = (attn_mask.sum(-1).astype(jnp.int32) if attn_mask is not None
               else jnp.full((B,), S, jnp.int32))
    attn = partial(sequence_parallel_attention, mesh=mesh, impl=impl,
                   kv_lens=kv_lens, causal=True)
    return forward(params, cfg, tokens, adapters=adapters, attn_fn=attn)


def prefill_seq_parallel(params: Params, cfg: LlamaConfig,
                         tokens: jnp.ndarray, mesh,
                         seq_lens: Optional[jnp.ndarray] = None,
                         adapters: Optional[Params] = None,
                         impl: str = "ring"):
    """Long-prompt prefill with the sequence dim sharded over mesh["seq"]:
    attention runs as ring attention while the per-layer K/V are COLLECTED
    for the serving cache — this is what turns §5.7 sequence parallelism
    into a serving capability (engine.prefill_long writes the result into
    the paged pool; ref has no counterpart — its long-context story is
    trimming retrieval to 1,500 tokens, utils.py:103).

    tokens: (B, S) right-padded, S divisible by the seq-axis size; callers
    place them with P("data", "seq"). Returns (last-position logits (B, V),
    k_stack, v_stack (L, B, S, kv_heads, head_dim) — seq-sharded like the
    activations).
    """
    from generativeaiexamples_tpu.parallel.ring_attention import (
        sequence_parallel_attention)

    if cfg.sliding_window:
        raise NotImplementedError(
            "sequence-parallel prefill is full-causal; sliding-window "
            "models use chunked prefill")
    B, S = tokens.shape
    if seq_lens is None:
        seq_lens = jnp.full((B,), S, jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
    h = embed_tokens(params, cfg, tokens)
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
    attn = partial(sequence_parallel_attention, mesh=mesh, impl=impl,
                   kv_lens=seq_lens, causal=True)

    def attn_and_update(q, k, v, _k, _v):
        return attn(q, k, v), k, v      # stash this layer's K/V via scan

    dummy = jnp.zeros((cfg.n_layers, 1), cfg.jdtype)
    h, k_stack, v_stack = scan_blocks(cfg, h, params, (dummy, dummy),
                                      cos, sin, attn_and_update, adapters)
    h_last = jnp.take_along_axis(
        h, (seq_lens - 1)[:, None, None].astype(jnp.int32), axis=1)
    return _unembed(cfg, params, h_last)[:, 0], k_stack, v_stack


def scan_blocks(cfg: LlamaConfig, h: jnp.ndarray, params: Params,
                kv_layers: Tuple[jnp.ndarray, jnp.ndarray],
                cos: jnp.ndarray, sin: jnp.ndarray, attn_and_update,
                adapters: Optional[Params]):
    """Scan the layer stack with per-layer KV-cache state threaded through.

    ``kv_layers`` is the (k, v) cache with a leading layer axis (any layout —
    dense (L, B, T, KV, HD) or paged (L, P, page, KV, HD)).
    ``attn_and_update(q, k_chunk, v_chunk, k_layer, v_layer) ->
    (ctx, new_k_layer, new_v_layer)`` owns both the cache write and the
    attention read, so dense prefill, dense decode, and block-table paged
    variants (engine/kv_cache.py) all share this one compiled block scan.
    """
    def body(h, xs):
        layer, k_l, v_l, ad = xs
        store = {}

        def attn(q, k, v):
            ctx, store["k"], store["v"] = attn_and_update(q, k, v, k_l, v_l)
            return ctx

        h, _ = _block(cfg, h, layer, cos, sin, attn, ad)  # aux unused serving
        return h, (store["k"], store["v"])

    h, (k_stack, v_stack) = jax.lax.scan(
        body, h, (params["layers"], kv_layers[0], kv_layers[1],
                  adapters or {}))
    return h, k_stack, v_stack


def scan_blocks_inplace(cfg: LlamaConfig, h: jnp.ndarray, params: Params,
                        pools: Tuple[jnp.ndarray, ...],
                        cos: jnp.ndarray, sin: jnp.ndarray, attn_and_update,
                        adapters: Optional[Params],
                        adapter_ix: Optional[jnp.ndarray] = None):
    """Layer scan with the FULL KV pool(s) as loop carry, updated in place.

    Unlike :func:`scan_blocks` (per-layer cache slices as scan inputs and
    freshly-stacked outputs — XLA copies the whole cache through the loop
    every call, ~2x the cache size in HBM traffic per decode step), the
    pools ride as while-loop carries: with the caller donating the buffers,
    XLA aliases the carry and each layer's write is a true in-place scatter.
    ``pools`` is any tuple of pool arrays (k, v [, k_scales, v_scales] for
    a quantized cache); ``attn_and_update(q, k_chunk, v_chunk, pools,
    layer_idx) -> (ctx, pools')`` owns the writes and the (paged)
    attention read — the token axis may even pack SEVERAL phases' rows
    (kv_cache.mixed_step concatenates every slot's decode positions with a
    prefill chunk and attends them as independent ragged rows).
    Returns (h, pools')."""
    def body(carry, xs):
        h, pools, idx = carry
        layer, ad = xs
        store = {}

        def attn(q, k, v):
            ctx, store["pools"] = attn_and_update(q, k, v, pools, idx)
            return ctx

        h, _ = _block(cfg, h, layer, cos, sin, attn, ad,
                      adapter_ix)              # aux unused when serving
        return (h, store["pools"], idx + 1), None

    (h, pools, _), _ = jax.lax.scan(
        body, (h, tuple(pools), jnp.int32(0)),
        (params["layers"], adapters or {}))
    return h, pools


def _scan_cached_blocks(cfg: LlamaConfig, h: jnp.ndarray, params: Params,
                        cache: KVCache, cos: jnp.ndarray, sin: jnp.ndarray,
                        write_pos: jnp.ndarray, attn_with_cache,
                        adapters: Optional[Params]):
    """Dense-cache specialization of :func:`scan_blocks`.

    The new K/V chunk is slice-written at ``write_pos`` per batch row; writes
    into a right-padded tail land garbage past seq_len, which stays masked and
    is overwritten by the next chunk / decode step — a plain
    `dynamic_update_slice` (fused by XLA) beats a masked scatter.
    ``attn_with_cache(q, k_cache_new, v_cache_new) -> ctx`` supplies the
    prefill vs decode attention math.
    """
    write = jax.vmap(lambda buf, upd, start: jax.lax.dynamic_update_slice(
        buf, upd, (start, jnp.int32(0), jnp.int32(0))))

    def attn_and_update(q, k, v, k_l, v_l):
        k_new = write(k_l, k.astype(k_l.dtype), write_pos)
        v_new = write(v_l, v.astype(v_l.dtype), write_pos)
        return attn_with_cache(q, k_new, v_new), k_new, v_new

    return scan_blocks(cfg, h, params, (cache.k, cache.v), cos, sin,
                       attn_and_update, adapters)


def prefill(params: Params, cfg: LlamaConfig, tokens: jnp.ndarray,
            cache: KVCache, start_pos: jnp.ndarray,
            seq_lens: jnp.ndarray,
            adapters: Optional[Params] = None,
            last_only: bool = False) -> Tuple[jnp.ndarray, KVCache]:
    """Prompt-processing pass that fills the dense KV cache.

    tokens: (B, S) right-padded prompts; start_pos: (B,) cache offset (0 for
    fresh sequences, >0 for chunked prefill); seq_lens: (B,) valid token
    counts in this chunk. Returns logits at each position (B, S, V) — or only
    at the last valid position (B, 1, V) when ``last_only`` (serving prefill
    needs one row; skipping the rest avoids a S×vocab unembed per admission)
    — and the updated cache (lengths = start_pos + seq_lens).
    """
    B, S = tokens.shape
    T = cache.k.shape[2]
    positions = start_pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None]
    h = embed_tokens(params, cfg, tokens)
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
    cache_positions = jnp.arange(T, dtype=jnp.int32)[None]
    kv_valid_through = (start_pos + seq_lens)

    use_pallas = (cfg.attn_impl == "pallas" and cfg.sliding_window == 0
                  and pallas_ops.prefill_supported(S, T, cfg.head_dim))

    def attn(q, k_new, v_new):
        if use_pallas:
            return pallas_ops.flash_prefill(
                q, k_new, v_new, start_pos=start_pos,
                kv_valid_through=kv_valid_through)
        kv_mask = cache_positions < kv_valid_through[:, None]
        return mha_prefill(q, k_new, v_new, q_positions=positions,
                           kv_positions=jnp.broadcast_to(cache_positions, (B, T)),
                           kv_mask=kv_mask, causal=True,
                           window=cfg.sliding_window)

    h, k_stack, v_stack = _scan_cached_blocks(
        cfg, h, params, cache, cos, sin, start_pos, attn, adapters)
    if last_only:
        h = jnp.take_along_axis(
            h, (seq_lens - 1)[:, None, None].astype(jnp.int32), axis=1)
    logits = _unembed(cfg, params, h)
    new_cache = KVCache(k=k_stack, v=v_stack, lengths=start_pos + seq_lens)
    return logits, new_cache


def decode_step(params: Params, cfg: LlamaConfig, tokens: jnp.ndarray,
                cache: KVCache,
                adapters: Optional[Params] = None) -> Tuple[jnp.ndarray, KVCache]:
    """One decode step for every sequence in the batch.

    tokens: (B,) last sampled token per slot. Uses cache.lengths as the
    position of the new token; returns logits (B, V) and the updated cache.
    """
    B = tokens.shape[0]
    T = cache.k.shape[2]
    positions = cache.lengths[:, None]                      # (B, 1)
    h = embed_tokens(params, cfg, tokens[:, None])       # (B, 1, D)
    cos, sin = rotary_embedding(positions, cfg.head_dim, cfg.rope_theta,
                                 scaling=cfg.rope_scaling)
    new_lengths = cache.lengths + 1

    use_pallas = (cfg.attn_impl == "pallas" and cfg.sliding_window == 0
                  and pallas_ops.decode_supported(T, cfg.head_dim))
    if use_pallas:
        attn = lambda q, k_new, v_new: pallas_ops.ragged_decode(
            q, k_new, v_new, new_lengths)
    else:
        attn = lambda q, k_new, v_new: mha_decode(
            q, k_new, v_new, new_lengths, window=cfg.sliding_window)

    h, k_stack, v_stack = _scan_cached_blocks(
        cfg, h, params, cache, cos, sin, cache.lengths, attn, adapters)
    logits = _unembed(cfg, params, h)[:, 0]
    return logits, KVCache(k=k_stack, v=v_stack, lengths=new_lengths)


# ---------------------------------------------------------------------------
# HuggingFace weight import (parity tests + local checkpoints)
# ---------------------------------------------------------------------------

def params_from_hf(state_dict: Dict[str, Any], cfg: LlamaConfig) -> Params:
    """Map a HF `LlamaForCausalLM.state_dict()` (torch tensors or ndarrays)
    into this layout. Linear weights transpose (torch keeps (out, in))."""
    import numpy as np

    def t(name):
        w = state_dict[name]
        arr = w.detach().cpu().numpy() if hasattr(w, "detach") else np.asarray(w)
        return jnp.asarray(arr, cfg.jdtype)

    def lin(name):  # torch Linear: (out, in) → (in, out)
        return t(name).T

    moe = cfg.mlp == "moe"
    mlp_keys = (("w_router", "w_gate", "w_up", "w_down") if moe
                else ("w_gate", "w_up", "w_down"))
    layers = {k: [] for k in ("attn_norm", "wq", "wk", "wv", "wo",
                              "mlp_norm", *mlp_keys)}
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        layers["attn_norm"].append(t(p + "input_layernorm.weight"))
        layers["wq"].append(lin(p + "self_attn.q_proj.weight"))
        layers["wk"].append(lin(p + "self_attn.k_proj.weight"))
        layers["wv"].append(lin(p + "self_attn.v_proj.weight"))
        layers["wo"].append(lin(p + "self_attn.o_proj.weight"))
        layers["mlp_norm"].append(t(p + "post_attention_layernorm.weight"))
        if moe:
            # MixtralForCausalLM layout: block_sparse_moe.gate (router) +
            # per-expert w1 (gate), w3 (up), w2 (down) → stacked on a
            # leading expert axis (ops/moe.py layout)
            b = p + "block_sparse_moe."
            layers["w_router"].append(lin(b + "gate.weight"))
            layers["w_gate"].append(jnp.stack(
                [lin(f"{b}experts.{e}.w1.weight")
                 for e in range(cfg.n_experts)]))
            layers["w_up"].append(jnp.stack(
                [lin(f"{b}experts.{e}.w3.weight")
                 for e in range(cfg.n_experts)]))
            layers["w_down"].append(jnp.stack(
                [lin(f"{b}experts.{e}.w2.weight")
                 for e in range(cfg.n_experts)]))
        else:
            layers["w_gate"].append(lin(p + "mlp.gate_proj.weight"))
            layers["w_up"].append(lin(p + "mlp.up_proj.weight"))
            layers["w_down"].append(lin(p + "mlp.down_proj.weight"))

    params: Params = {
        "embed": t("model.embed_tokens.weight"),
        "layers": {k: jnp.stack(v) for k, v in layers.items()},
        "final_norm": t("model.norm.weight"),
    }
    if not cfg.tie_embeddings:
        key = "lm_head.weight"
        params["lm_head"] = (t(key).T if key in state_dict
                             else t("model.embed_tokens.weight").T)
    return params
