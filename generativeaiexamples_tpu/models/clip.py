"""CLIP-class dual-tower vision/text encoder — functional JAX, TPU-first.

The in-tree counterpart of the reference's hosted vision encoders (ref:
vision_workflows/README.md — "NVCLIP Multimodal Search" and "NV-DINOv2"
workflows run NIM containers; RAG/examples/advanced_rag/multimodal_rag uses a
served VLM). One joint-embedding model covers both roles: the vision tower is
a ViT usable alone (DINOv2-style image features), and with the text tower it
does zero-shot scoring and text↔image retrieval.

Design mirrors models/llama.py:
  * per-layer tensors stacked on a leading layer axis, block applied with
    `lax.scan` — one compiled block per tower regardless of depth;
  * logical-axis annotations per leaf (`logical_axes`) so parallel.sharding
    rule tables place the towers on a mesh without the model naming axes;
  * patch embedding as an unfold+matmul (XLA fuses it into one big GEMM on
    the MXU — no conv primitive needed at stride == kernel);
  * QuickGELU and pre-LayerNorm per the original CLIP architecture, so
    `params_from_hf` maps a HuggingFace `CLIPModel.state_dict()` for real
    checkpoints (openai/clip-vit-* family); random init serves tests.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclass(frozen=True)
class ClipConfig:
    # vision tower
    image_size: int = 224
    patch_size: int = 32
    vision_dim: int = 768
    vision_layers: int = 12
    vision_heads: int = 12
    # text tower
    vocab_size: int = 49408
    max_text_len: int = 77
    text_dim: int = 512
    text_layers: int = 12
    text_heads: int = 8
    # joint space
    projection_dim: int = 512
    norm_eps: float = 1e-5
    dtype: str = "float32"

    @staticmethod
    def vit_b32() -> "ClipConfig":
        return ClipConfig()

    @staticmethod
    def tiny() -> "ClipConfig":
        """Deterministic test-scale config (SURVEY §4 fake-backend style)."""
        return ClipConfig(image_size=32, patch_size=8, vision_dim=32,
                          vision_layers=2, vision_heads=2, vocab_size=300,
                          max_text_len=16, text_dim=32, text_layers=2,
                          text_heads=2, projection_dim=16)

    @property
    def jdtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def _tower_init(rng, L: int, D: int, dt) -> Params:
    keys = jax.random.split(rng, 6)

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dt)

    return {
        "ln1_w": jnp.ones((L, D), dt), "ln1_b": jnp.zeros((L, D), dt),
        "wqkv": normal(keys[0], (L, D, 3 * D), D),
        "bqkv": jnp.zeros((L, 3 * D), dt),
        "wo": normal(keys[1], (L, D, D), D), "bo": jnp.zeros((L, D), dt),
        "ln2_w": jnp.ones((L, D), dt), "ln2_b": jnp.zeros((L, D), dt),
        "w_up": normal(keys[2], (L, D, 4 * D), D),
        "b_up": jnp.zeros((L, 4 * D), dt),
        "w_down": normal(keys[3], (L, 4 * D, D), 4 * D),
        "b_down": jnp.zeros((L, D), dt),
    }


def init_params(rng: jax.Array, cfg: ClipConfig) -> Params:
    dt = cfg.jdtype
    (kv, kt, k1, k2, k3, k4, k5, k6, k7) = jax.random.split(rng, 9)
    patch_in = 3 * cfg.patch_size ** 2

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dt)

    return {
        "vision": {
            "patch_embed": normal(k1, (patch_in, cfg.vision_dim), patch_in),
            "class_embed": normal(k2, (cfg.vision_dim,), cfg.vision_dim),
            "pos_embed": normal(k3, (cfg.n_patches + 1, cfg.vision_dim),
                                cfg.vision_dim),
            "pre_ln_w": jnp.ones((cfg.vision_dim,), dt),
            "pre_ln_b": jnp.zeros((cfg.vision_dim,), dt),
            "layers": _tower_init(kv, cfg.vision_layers, cfg.vision_dim, dt),
            "post_ln_w": jnp.ones((cfg.vision_dim,), dt),
            "post_ln_b": jnp.zeros((cfg.vision_dim,), dt),
            "proj": normal(k4, (cfg.vision_dim, cfg.projection_dim),
                           cfg.vision_dim),
        },
        "text": {
            "tok_embed": normal(k5, (cfg.vocab_size, cfg.text_dim),
                                cfg.text_dim),
            "pos_embed": normal(k6, (cfg.max_text_len, cfg.text_dim),
                                cfg.text_dim),
            "layers": _tower_init(kt, cfg.text_layers, cfg.text_dim, dt),
            "final_ln_w": jnp.ones((cfg.text_dim,), dt),
            "final_ln_b": jnp.zeros((cfg.text_dim,), dt),
            "proj": normal(k7, (cfg.text_dim, cfg.projection_dim),
                           cfg.text_dim),
        },
        "logit_scale": jnp.asarray(math.log(1 / 0.07), dt),
    }


def logical_axes(cfg: ClipConfig) -> Params:
    def tower(_):
        return {
            "ln1_w": (None, "embed"), "ln1_b": (None, "embed"),
            "wqkv": (None, "embed", "heads"), "bqkv": (None, "heads"),
            "wo": (None, "heads", "embed"), "bo": (None, "embed"),
            "ln2_w": (None, "embed"), "ln2_b": (None, "embed"),
            "w_up": (None, "embed", "mlp"), "b_up": (None, "mlp"),
            "w_down": (None, "mlp", "embed"), "b_down": (None, "embed"),
        }
    return {
        "vision": {
            "patch_embed": (None, "embed"),
            "class_embed": ("embed",),
            "pos_embed": (None, "embed"),
            "pre_ln_w": ("embed",), "pre_ln_b": ("embed",),
            "layers": tower(None),
            "post_ln_w": ("embed",), "post_ln_b": ("embed",),
            "proj": ("embed", None),
        },
        "text": {
            "tok_embed": ("vocab_table", "embed_table"),
            "pos_embed": (None, "embed"),
            "layers": tower(None),
            "final_ln_w": ("embed",), "final_ln_b": ("embed",),
            "proj": ("embed", None),
        },
        "logit_scale": (),
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layer_norm(x, w, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * w + b


def _quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


def _encoder(cfg: ClipConfig, h: jnp.ndarray, tower: Params, n_heads: int,
             causal: bool) -> jnp.ndarray:
    """Pre-LN transformer encoder over stacked layers via lax.scan."""
    B, S, D = h.shape
    HD = D // n_heads
    mask = (jnp.tril(jnp.ones((S, S), bool)) if causal else None)

    def block(h, layer):
        x = _layer_norm(h, layer["ln1_w"], layer["ln1_b"], cfg.norm_eps)
        qkv = x @ layer["wqkv"] + layer["bqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, n_heads, HD).transpose(0, 2, 1, 3)
        k = k.reshape(B, S, n_heads, HD).transpose(0, 2, 1, 3)
        v = v.reshape(B, S, n_heads, HD).transpose(0, 2, 1, 3)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(HD)
        if mask is not None:
            s = jnp.where(mask[None, None], s, -1e30)
        ctx = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, D)
        h = h + ctx @ layer["wo"] + layer["bo"]
        x = _layer_norm(h, layer["ln2_w"], layer["ln2_b"], cfg.norm_eps)
        h = h + _quick_gelu(x @ layer["w_up"] + layer["b_up"]) @ layer["w_down"] + layer["b_down"]
        return h, None

    h, _ = jax.lax.scan(block, h, tower["layers"])
    return h


def encode_image(params: Params, cfg: ClipConfig,
                 pixels: jnp.ndarray) -> jnp.ndarray:
    """pixels (B, H, W, 3) normalized → joint-space embeddings (B, P).

    Patch embedding is unfold+matmul: (B, H/p, p, W/p, p, 3) → a (B, N,
    3p²)·(3p², D) GEMM — stride==kernel convolution expressed MXU-natively.
    """
    v = params["vision"]
    h = _patchify_embed(cfg, v, pixels)
    h = _encoder(cfg, h, v, cfg.vision_heads, causal=False)
    pooled = _layer_norm(h[:, 0], v["post_ln_w"], v["post_ln_b"],
                         cfg.norm_eps)
    return pooled @ v["proj"]


def _patchify_embed(cfg: ClipConfig, v: Params,
                    pixels: jnp.ndarray) -> jnp.ndarray:
    """Shared vision preamble: unfold+matmul patch embedding, CLS prepend,
    positional embeddings, pre-LN → (B, n_patches+1, vision_dim)."""
    B = pixels.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    x = pixels.reshape(B, g, p, g, p, 3).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(B, g * g, p * p * 3)
    h = x.astype(cfg.jdtype) @ v["patch_embed"]
    cls = jnp.broadcast_to(v["class_embed"], (B, 1, cfg.vision_dim))
    h = jnp.concatenate([cls, h], axis=1) + v["pos_embed"][None]
    return _layer_norm(h, v["pre_ln_w"], v["pre_ln_b"], cfg.norm_eps)


def encode_image_features(params: Params, cfg: ClipConfig,
                          pixels: jnp.ndarray,
                          drop_last_layers: int = 1,
                          keep_cls: bool = False) -> jnp.ndarray:
    """Per-patch hidden states for VLM conditioning (models/vlm.py):
    pixels (B, H, W, 3) → (B, n_patches[+1], vision_dim) taken BEFORE the
    last ``drop_last_layers`` encoder blocks. ``keep_cls`` retains the CLS
    row (LLaVA vision_feature_select_strategy "full"); the default drops
    it ("default" strategy, vision_feature_layer=-2 ↔ drop_last_layers=1)."""
    v = params["vision"]
    h = _patchify_embed(cfg, v, pixels)
    keep = cfg.vision_layers - drop_last_layers
    truncated = dict(v)
    truncated["layers"] = jax.tree.map(lambda w: w[:keep], v["layers"])
    h = _encoder(cfg, h, truncated, cfg.vision_heads, causal=False)
    return h if keep_cls else h[:, 1:, :]


def encode_text(params: Params, cfg: ClipConfig, tokens: jnp.ndarray,
                eos_positions: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """tokens (B, S) right-padded → joint-space embeddings (B, P).

    The pooled feature is the hidden state at the sequence's EOS position
    (HF CLIPTextModel semantics); ``eos_positions`` defaults to the last
    position of each row.
    """
    t = params["text"]
    B, S = tokens.shape
    if eos_positions is None:
        eos_positions = jnp.full((B,), S - 1, jnp.int32)
    h = t["tok_embed"].astype(cfg.jdtype)[tokens] + t["pos_embed"][None, :S]
    h = _encoder(cfg, h, t, cfg.text_heads, causal=True)
    h = _layer_norm(h, t["final_ln_w"], t["final_ln_b"], cfg.norm_eps)
    pooled = jnp.take_along_axis(
        h, eos_positions[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    return pooled @ t["proj"]


def similarity(params: Params, image_emb: jnp.ndarray,
               text_emb: jnp.ndarray) -> jnp.ndarray:
    """Temperature-scaled cosine logits (n_img, n_text)."""
    img = image_emb / jnp.linalg.norm(image_emb, axis=-1, keepdims=True)
    txt = text_emb / jnp.linalg.norm(text_emb, axis=-1, keepdims=True)
    return jnp.exp(params["logit_scale"]) * img @ txt.T


# ---------------------------------------------------------------------------
# HuggingFace weight import (CLIPModel.state_dict())
# ---------------------------------------------------------------------------

def _hf_importers(state_dict: Dict[str, Any], cfg: ClipConfig):
    import numpy as np

    def t(name):
        w = state_dict[name]
        arr = w.detach().cpu().numpy() if hasattr(w, "detach") else np.asarray(w)
        return jnp.asarray(arr, cfg.jdtype)

    def lin(name):
        return t(name).T

    def tower(prefix: str, n_layers: int) -> Params:
        acc = {k: [] for k in ("ln1_w", "ln1_b", "wqkv", "bqkv", "wo", "bo",
                               "ln2_w", "ln2_b", "w_up", "b_up", "w_down",
                               "b_down")}
        for i in range(n_layers):
            p = f"{prefix}.encoder.layers.{i}."
            acc["ln1_w"].append(t(p + "layer_norm1.weight"))
            acc["ln1_b"].append(t(p + "layer_norm1.bias"))
            acc["wqkv"].append(jnp.concatenate([
                lin(p + "self_attn.q_proj.weight"),
                lin(p + "self_attn.k_proj.weight"),
                lin(p + "self_attn.v_proj.weight")], axis=1))
            acc["bqkv"].append(jnp.concatenate([
                t(p + "self_attn.q_proj.bias"),
                t(p + "self_attn.k_proj.bias"),
                t(p + "self_attn.v_proj.bias")]))
            acc["wo"].append(lin(p + "self_attn.out_proj.weight"))
            acc["bo"].append(t(p + "self_attn.out_proj.bias"))
            acc["ln2_w"].append(t(p + "layer_norm2.weight"))
            acc["ln2_b"].append(t(p + "layer_norm2.bias"))
            acc["w_up"].append(lin(p + "mlp.fc1.weight"))
            acc["b_up"].append(t(p + "mlp.fc1.bias"))
            acc["w_down"].append(lin(p + "mlp.fc2.weight"))
            acc["b_down"].append(t(p + "mlp.fc2.bias"))
        return {k: jnp.stack(v) for k, v in acc.items()}

    return t, lin, tower


def vision_params_from_hf(state_dict: Dict[str, Any], cfg: ClipConfig,
                          with_projection: bool = True) -> Params:
    """Vision tower only (VLM checkpoints ship no CLIP text tower — ref
    Llava's vision_tower.* keys). ``with_projection=False`` fills the
    unused joint-space projection with an identity-free zero stub so
    `encode_image_features` consumers pay no text-tower memory."""
    t, lin, tower = _hf_importers(state_dict, cfg)
    # HF conv patch embed: (D, 3, p, p) → unfold layout (p*p*3, D) matching
    # encode_image's (row-major patch pixels, channel minor) flattening
    conv = state_dict["vision_model.embeddings.patch_embedding.weight"]
    conv = conv.detach().cpu().numpy() if hasattr(conv, "detach") else conv
    patch = jnp.asarray(conv, cfg.jdtype).transpose(2, 3, 1, 0).reshape(
        cfg.patch_size * cfg.patch_size * 3, cfg.vision_dim)
    proj = (lin("visual_projection.weight") if with_projection
            else jnp.zeros((cfg.vision_dim, cfg.projection_dim), cfg.jdtype))
    return {
        "patch_embed": patch,
        "class_embed": t("vision_model.embeddings.class_embedding"),
        "pos_embed": t("vision_model.embeddings.position_embedding.weight"),
        "pre_ln_w": t("vision_model.pre_layrnorm.weight"),
        "pre_ln_b": t("vision_model.pre_layrnorm.bias"),
        "layers": tower("vision_model", cfg.vision_layers),
        "post_ln_w": t("vision_model.post_layernorm.weight"),
        "post_ln_b": t("vision_model.post_layernorm.bias"),
        "proj": proj,
    }


def params_from_hf(state_dict: Dict[str, Any], cfg: ClipConfig) -> Params:
    """Map a HF `CLIPModel.state_dict()` (torch tensors or ndarrays) into
    this layout. Linear weights transpose (torch keeps (out, in)); per-layer
    q/k/v projections concatenate into the stacked wqkv."""
    t, lin, tower = _hf_importers(state_dict, cfg)
    return {
        "vision": vision_params_from_hf(state_dict, cfg),
        "text": {
            "tok_embed": t("text_model.embeddings.token_embedding.weight"),
            "pos_embed": t("text_model.embeddings.position_embedding.weight"),
            "layers": tower("text_model", cfg.text_layers),
            "final_ln_w": t("text_model.final_layer_norm.weight"),
            "final_ln_b": t("text_model.final_layer_norm.bias"),
            "proj": lin("text_projection.weight"),
        },
        "logit_scale": t("logit_scale"),
    }
