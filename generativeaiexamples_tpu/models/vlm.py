"""LLaVA-architecture vision-language model: CLIP tower → projector → llama.

The reference's VLM capability is a hosted endpoint (`multimodal_invoke`,
ref RAG/examples/advanced_rag/multimodal_rag/llm/llm_client.py:48, and the
Nemotron Nano VLM notebook, ref nemotron/VLM/llama_3.1_nemotron_nano_VL_8B).
This is the in-tree TPU-native family behind the same seam: patch features
from the CLIP vision tower (penultimate layer, CLS dropped —
vision_feature_layer=-2 / "default"), a two-layer GELU projector into the
decoder's embedding space, and the llama block stack consuming a sequence
whose ``<image>`` token positions were replaced by the projected patch
embeddings (HF Llava's masked-scatter semantics, so checkpoints import
and parity-test directly against `LlavaForConditionalGeneration`).

All three sub-models are the existing functional implementations —
`models/clip.py` and `models/llama.py` — so mesh sharding rules and the
family knobs compose; `generate` is a plain greedy loop over `forward`
(capability/eval path; engine-paged VLM serving would splice features at
prefill, which the chunked prefill already supports via input embeds).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from generativeaiexamples_tpu.models import clip as clip_lib
from generativeaiexamples_tpu.models import llama as llama_lib

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class VlmConfig:
    clip: clip_lib.ClipConfig
    llm: llama_lib.LlamaConfig
    image_token_id: int = 32000
    vision_feature_drop: int = 1    # take hidden states before the last N
    vision_feature_select: str = "default"   # "default" (drop CLS) | "full"
    projector_hidden: int = 0       # 0 = llm dim

    @staticmethod
    def tiny(vocab_size: int = 256) -> "VlmConfig":
        return VlmConfig(clip=clip_lib.ClipConfig.tiny(),
                         llm=llama_lib.LlamaConfig.tiny(vocab_size),
                         image_token_id=vocab_size - 1)

    @property
    def n_image_tokens(self) -> int:
        return self.clip.n_patches + (
            1 if self.vision_feature_select == "full" else 0)


def init_params(rng: jax.Array, cfg: VlmConfig) -> Params:
    import math

    k1, k2, k3, k4 = jax.random.split(rng, 4)
    D_in, D_out = cfg.clip.vision_dim, cfg.llm.dim
    hidden = cfg.projector_hidden or D_out
    dt = cfg.llm.jdtype

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dt)

    return {
        "clip": clip_lib.init_params(k1, cfg.clip),
        "projector": {
            "w1": normal(k2, (D_in, hidden), D_in),
            "b1": jnp.zeros((hidden,), dt),
            "w2": normal(k3, (hidden, D_out), hidden),
            "b2": jnp.zeros((D_out,), dt),
        },
        "llm": llama_lib.init_params(k4, cfg.llm),
    }


def image_features(params: Params, cfg: VlmConfig,
                   pixels: jnp.ndarray) -> jnp.ndarray:
    """pixels (B, H, W, 3) → projected patch embeddings (B, N, llm_dim)."""
    feats = clip_lib.encode_image_features(
        params["clip"], cfg.clip, pixels,
        drop_last_layers=cfg.vision_feature_drop,
        keep_cls=cfg.vision_feature_select == "full")
    p = params["projector"]
    h = feats.astype(p["w1"].dtype) @ p["w1"] + p["b1"]
    h = jax.nn.gelu(h, approximate=False)
    return h @ p["w2"] + p["b2"]


def splice_images(params: Params, cfg: VlmConfig, tokens: jnp.ndarray,
                  feats: jnp.ndarray) -> jnp.ndarray:
    """Token embeddings with ``<image>`` positions replaced by patch
    features in order (HF masked_scatter semantics). tokens (B, S) must
    contain exactly ``n_image_tokens`` image tokens per row."""
    embeds = llama_lib.embed_tokens(params["llm"], cfg.llm, tokens)
    B, S, D = embeds.shape
    is_img = tokens == cfg.image_token_id                     # (B, S)
    # k-th image token in a row receives feats[row, k]
    ordinal = jnp.cumsum(is_img, axis=1) - 1                  # (B, S)
    gathered = jnp.take_along_axis(
        feats.astype(embeds.dtype),
        jnp.clip(ordinal, 0, feats.shape[1] - 1)[..., None], axis=1)
    return jnp.where(is_img[..., None], gathered, embeds)


def forward(params: Params, cfg: VlmConfig, pixels: jnp.ndarray,
            tokens: jnp.ndarray) -> jnp.ndarray:
    """Causal-LM logits (B, S, vocab) over text conditioned on images."""
    feats = image_features(params, cfg, pixels)
    embeds = splice_images(params, cfg, tokens, feats)
    return llama_lib.forward(params["llm"], cfg.llm, tokens,
                             input_embeds=embeds)


def build_prompt(cfg: VlmConfig, text_ids, bos_id: Optional[int] = None
                 ) -> list:
    """[bos] + <image>*N + text — the single-image LLaVA layout with the
    image token pre-expanded to its patch count."""
    ids = [bos_id] if bos_id is not None else []
    ids += [cfg.image_token_id] * cfg.n_image_tokens
    return ids + list(text_ids)


def generate(params: Params, cfg: VlmConfig, pixels: jnp.ndarray,
             prompt_ids, max_tokens: int = 32,
             eos_id: Optional[int] = None) -> list:
    """Greedy continuation (capability/eval path: full re-forward per step;
    throughput serving goes through the paged engine with spliced prefill
    embeds)."""
    feats = image_features(params, cfg, pixels)
    seq = list(prompt_ids)
    out = []
    for _ in range(max_tokens):
        toks = jnp.asarray([seq], jnp.int32)
        embeds = splice_images(params, cfg, toks, feats)
        logits = llama_lib.forward(params["llm"], cfg.llm, toks,
                                   input_embeds=embeds)
        # the image placeholder must never be GENERATED: its lm_head row is
        # untrained, and appending it would make the next step's splice
        # overwrite a text position with a patch feature
        step_logits = logits[0, -1].at[cfg.image_token_id].set(-jnp.inf)
        nxt = int(jnp.argmax(step_logits))
        if eos_id is not None and nxt == eos_id:
            break
        seq.append(nxt)
        out.append(nxt)
    return out


def params_from_hf(state_dict: Dict[str, Any], cfg: VlmConfig) -> Params:
    """Map a HF `LlavaForConditionalGeneration.state_dict()` into this
    layout: vision tower via the clip vision-only importer
    (prefix-stripped; Llava ships no CLIP text tower and no visual
    projection), the multi-modal projector's two linears, language model
    via the llama importer."""
    import numpy as np

    def sub(prefix: str) -> Dict[str, Any]:
        return {k[len(prefix):]: v for k, v in state_dict.items()
                if k.startswith(prefix)}

    vision_sd = sub("model.vision_tower.")
    if not vision_sd:
        vision_sd = sub("vision_tower.")
    clip_params = {"vision": clip_lib.vision_params_from_hf(
        vision_sd, cfg.clip, with_projection=False)}

    proj = sub("model.multi_modal_projector.")
    if not proj:
        proj = sub("multi_modal_projector.")

    def lin(d, name):
        w = d[name]
        arr = w.detach().cpu().numpy() if hasattr(w, "detach") else np.asarray(w)
        return jnp.asarray(arr, cfg.llm.jdtype).T

    def vec(d, name):
        w = d[name]
        arr = w.detach().cpu().numpy() if hasattr(w, "detach") else np.asarray(w)
        return jnp.asarray(arr, cfg.llm.jdtype)

    llm_sd = sub("model.language_model.")
    if llm_sd:
        # newer HF layout: model.language_model.* + top-level lm_head
        llm_sd = {f"model.{k}": v for k, v in llm_sd.items()}
        if "lm_head.weight" in state_dict:
            llm_sd["lm_head.weight"] = state_dict["lm_head.weight"]
    else:
        llm_sd = sub("language_model.")

    return {
        "clip": clip_params,
        "projector": {
            "w1": lin(proj, "linear_1.weight"),
            "b1": vec(proj, "linear_1.bias"),
            "w2": lin(proj, "linear_2.weight"),
            "b2": vec(proj, "linear_2.bias"),
        },
        "llm": llama_lib.params_from_hf(llm_sd, cfg.llm),
    }


def config_from_hf(hf_cfg) -> VlmConfig:
    """VlmConfig from a HF `LlavaConfig` (or its dict)."""
    if isinstance(hf_cfg, dict):
        v, t = hf_cfg["vision_config"], hf_cfg["text_config"]
        get_v = v.get
        get_t = t.get
        image_token = hf_cfg.get("image_token_index", 32000)
        feature_layer = int(hf_cfg.get("vision_feature_layer", -2))
        select = str(hf_cfg.get("vision_feature_select_strategy", "default"))
    else:
        v, t = hf_cfg.vision_config, hf_cfg.text_config
        get_v = lambda k, d=None: getattr(v, k, d)
        get_t = lambda k, d=None: getattr(t, k, d)
        image_token = getattr(hf_cfg, "image_token_index", 32000)
        feature_layer = int(getattr(hf_cfg, "vision_feature_layer", -2))
        select = str(getattr(hf_cfg, "vision_feature_select_strategy",
                             "default"))
    # HF serializes nested sub-configs as DIFFS against their class
    # defaults (llava-1.5-7b-hf's text_config omits hidden_size entirely)
    # — every lookup must fall back to the HF CLIPVisionConfig/LlamaConfig
    # default, not None
    clip_cfg = clip_lib.ClipConfig(
        image_size=get_v("image_size", 224) or 224,
        patch_size=get_v("patch_size", 32) or 32,
        vision_dim=get_v("hidden_size", 768) or 768,
        vision_layers=get_v("num_hidden_layers", 12) or 12,
        vision_heads=get_v("num_attention_heads", 12) or 12,
        projection_dim=get_v("projection_dim", 512) or 512)
    dim = get_t("hidden_size", 4096) or 4096
    n_heads = get_t("num_attention_heads", 32) or 32
    head_dim = get_t("head_dim") or dim // n_heads
    llm_cfg = llama_lib.LlamaConfig(
        vocab_size=get_t("vocab_size", 32000) or 32000,
        dim=dim,
        n_layers=get_t("num_hidden_layers", 32) or 32,
        n_heads=n_heads,
        n_kv_heads=get_t("num_key_value_heads") or n_heads,
        hidden_dim=get_t("intermediate_size", 11008) or 11008,
        head_dim=head_dim,
        rope_theta=float(get_t("rope_theta", 10000.0) or 10000.0),
        norm_eps=float(get_t("rms_norm_eps", 1e-6) or 1e-6),
        tie_embeddings=bool(get_t("tie_word_embeddings", False)),
        dtype="bfloat16")
    # HF indexes the hidden_states list (length L+1, entry i = after block
    # i): -2 → drop 1 trailing block, -1 → drop 0, positive p → drop L - p
    L = clip_cfg.vision_layers
    drop = (-feature_layer - 1) if feature_layer < 0 else (L - feature_layer)
    if not 0 <= drop <= L:
        raise ValueError(f"vision_feature_layer {feature_layer} out of "
                         f"range for {L} blocks")
    if select not in ("default", "full"):
        raise ValueError(f"unsupported vision_feature_select_strategy "
                         f"{select!r}")
    return VlmConfig(clip=clip_cfg, llm=llm_cfg,
                     image_token_id=image_token,
                     vision_feature_drop=drop,
                     vision_feature_select=select)


def load_checkpoint(checkpoint_dir: str) -> Tuple[VlmConfig, Params]:
    """Load a local HF Llava checkpoint directory (config.json +
    safetensors/bin shards) into (VlmConfig, params)."""
    import glob as globlib
    import json
    import os

    with open(os.path.join(checkpoint_dir, "config.json")) as fh:
        cfg = config_from_hf(json.load(fh))
    state: Dict[str, Any] = {}
    shards = sorted(globlib.glob(os.path.join(checkpoint_dir,
                                              "*.safetensors")))
    if shards:
        from safetensors import safe_open

        for shard in shards:
            with safe_open(shard, framework="np") as f:
                for key in f.keys():
                    state[key] = f.get_tensor(key)
    else:
        import torch

        for shard in sorted(globlib.glob(
                os.path.join(checkpoint_dir, "pytorch_model*.bin"))):
            state.update(torch.load(shard, map_location="cpu",
                                    weights_only=True))
    if not state:
        raise FileNotFoundError(
            f"no safetensors/bin weights under {checkpoint_dir}")
    return cfg, params_from_hf(state, cfg)
