"""Whisper-class speech recognition in JAX: log-mel frontend + enc-dec model.

Fills the Riva-ASR slot (SURVEY §2.5) with an IN-TREE model, so the
playground's voice loop (record → transcribe → converse → speak) runs with
zero external services — the round-3 gap where speech worked only against
an external OpenAI-audio endpoint (ref: the reference's Riva client,
RAG/src/rag_playground/speech/asr_utils.py:117-167; its server side is an
external container, like every model service in the reference).

Design, TPU-first rather than a port of openai/whisper's torch code:

  * the audio frontend (framing → Hann window → |rFFT|² → Slaney mel
    filterbank → log compression) is plain numpy on the host — it is
    O(seconds of audio) and runs once per request;
  * the model is pure functions over a params pytree like models/llama.py:
    encoder = 2 convs (stride-2 downsample) + pre-LN transformer with
    fixed sinusoidal positions; decoder = token+learned-position embedding
    + pre-LN blocks with causal self-attention and encoder cross-attention,
    logits tied to the token embedding;
  * `params_from_hf` maps a HuggingFace WhisperForConditionalGeneration
    state_dict (e.g. openai/whisper-tiny) onto the tree — numerical parity
    is pinned by tests/test_whisper.py against a randomly-initialized HF
    module, the same no-network pattern as models/vlm.py;
  * greedy transcription runs ONE fixed-shape cached decode step program
    (per-block self-attention KV cache + precomputed cross-attention K/V),
    O(n) per utterance.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, object]


@dataclasses.dataclass(frozen=True)
class WhisperConfig:
    vocab_size: int = 51865
    d_model: int = 384
    n_heads: int = 6
    enc_layers: int = 4
    dec_layers: int = 4
    n_mels: int = 80
    n_audio_frames: int = 3000        # 30 s of 10 ms hops, pre-conv
    n_text_ctx: int = 448
    sample_rate: int = 16000
    n_fft: int = 400
    hop: int = 160
    # special token ids (openai/whisper-tiny multilingual vocabulary)
    sot: int = 50258
    eot: int = 50257
    lang_en: int = 50259
    task_transcribe: int = 50359
    no_timestamps: int = 50363

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def n_audio_ctx(self) -> int:
        return self.n_audio_frames // 2   # conv2 stride 2

    @staticmethod
    def tiny_random(vocab_size: int = 320) -> "WhisperConfig":
        """Test-scale config (random init; specials folded into the vocab)."""
        return WhisperConfig(vocab_size=vocab_size, d_model=64, n_heads=2,
                             enc_layers=2, dec_layers=2,
                             n_audio_frames=200, n_text_ctx=64,
                             sot=300, eot=301, lang_en=302,
                             task_transcribe=303, no_timestamps=304)


# ---------------------------------------------------------------------------
# Audio frontend (host-side numpy)
# ---------------------------------------------------------------------------

def mel_filterbank(sr: int, n_fft: int, n_mels: int) -> np.ndarray:
    """Slaney-style mel filterbank, (n_mels, n_fft//2+1) — the librosa
    default whisper's preprocessing uses (linear below 1 kHz, log above,
    area-normalized triangles)."""
    def hz_to_mel(f):
        f = np.asarray(f, np.float64)
        mel = f / (200.0 / 3.0)
        log_region = f >= 1000.0
        mel = np.where(log_region,
                       15.0 + np.log(np.maximum(f, 1e-10) / 1000.0)
                       / np.log(6.4) * 27.0, mel)
        return mel

    def mel_to_hz(m):
        m = np.asarray(m, np.float64)
        f = m * (200.0 / 3.0)
        log_region = m >= 15.0
        return np.where(log_region, 1000.0 * np.exp(np.log(6.4)
                                                    * (m - 15.0) / 27.0), f)

    fft_freqs = np.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_pts = mel_to_hz(np.linspace(hz_to_mel(0.0), hz_to_mel(sr / 2.0),
                                    n_mels + 2))
    weights = np.zeros((n_mels, n_fft // 2 + 1))
    for i in range(n_mels):
        lo, ctr, hi = mel_pts[i], mel_pts[i + 1], mel_pts[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        weights[i] = np.maximum(0.0, np.minimum(up, down))
        weights[i] *= 2.0 / (hi - lo)             # Slaney area norm
    return weights.astype(np.float32)


def log_mel(audio: np.ndarray, cfg: WhisperConfig) -> np.ndarray:
    """float32 mono 16 kHz samples → (n_mels, n_audio_frames) log-mel.
    The AUDIO pads/trims to the fixed window first (whisper's pad_or_trim
    convention): short clips' tail frames are then true silence run through
    the same log/clamp/rescale, not out-of-distribution zero columns."""
    n_samples = cfg.n_audio_frames * cfg.hop
    audio = audio.astype(np.float32)
    if len(audio) < n_samples:
        audio = np.pad(audio, (0, n_samples - len(audio)))
    audio = audio[:n_samples]
    window = np.hanning(cfg.n_fft + 1)[:-1].astype(np.float32)
    pad = cfg.n_fft // 2
    x = np.pad(audio, (pad, pad), mode="reflect")
    n_frames = 1 + (len(x) - cfg.n_fft) // cfg.hop
    frames = np.lib.stride_tricks.sliding_window_view(
        x, cfg.n_fft)[:: cfg.hop][:n_frames]
    power = np.abs(np.fft.rfft(frames * window, axis=-1)) ** 2
    mel = mel_filterbank(cfg.sample_rate, cfg.n_fft, cfg.n_mels) @ power.T
    log_spec = np.log10(np.maximum(mel, 1e-10))
    log_spec = np.maximum(log_spec, log_spec.max() - 8.0)
    log_spec = (log_spec + 4.0) / 4.0
    return log_spec[:, : cfg.n_audio_frames].astype(np.float32)


def pcm16_to_float(audio: bytes) -> np.ndarray:
    """Raw little-endian PCM16 → float32 [-1, 1]."""
    return (np.frombuffer(audio[: len(audio) // 2 * 2], np.int16)
            .astype(np.float32) / 32768.0)


def _pcm_to_float(raw: bytes, sampwidth: int) -> np.ndarray:
    """PCM at 1/2/4-byte widths → float32 [-1, 1] (loud failure otherwise —
    silently reinterpreting 24/32-bit as int16 pairs transcribes noise)."""
    if sampwidth == 2:
        return pcm16_to_float(raw)
    if sampwidth == 1:      # WAV 8-bit is unsigned
        return (np.frombuffer(raw, np.uint8).astype(np.float32) - 128.0) / 128.0
    if sampwidth == 4:
        return (np.frombuffer(raw[: len(raw) // 4 * 4], np.int32)
                .astype(np.float32) / 2147483648.0)
    raise ValueError(f"unsupported WAV sample width {sampwidth} bytes")


def decode_wav(data: bytes, target_sr: int) -> np.ndarray:
    """RIFF/WAV (8/16/32-bit PCM) → mono float32 at target_sr (linear
    resample); non-RIFF bytes are treated as raw PCM16 mono at target_sr."""
    if data[:4] != b"RIFF":
        return pcm16_to_float(data)
    import io
    import wave
    with wave.open(io.BytesIO(data)) as w:
        sr, ch = w.getframerate(), w.getnchannels()
        pcm = _pcm_to_float(w.readframes(w.getnframes()), w.getsampwidth())
    if ch > 1:
        pcm = pcm[: len(pcm) // ch * ch].reshape(-1, ch).mean(axis=1)
    if sr != target_sr and len(pcm) > 1:
        n_out = int(len(pcm) * target_sr / sr)
        pcm = np.interp(np.linspace(0, len(pcm) - 1, n_out),
                        np.arange(len(pcm)), pcm).astype(np.float32)
    return pcm


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

def _sinusoids(length: int, channels: int) -> np.ndarray:
    """Whisper's fixed audio positions: sin/cos with log-spaced timescales."""
    scale = np.log(10000.0) / (channels // 2 - 1)
    inv = np.exp(-scale * np.arange(channels // 2))
    t = np.arange(length)[:, None] * inv[None, :]
    return np.concatenate([np.sin(t), np.cos(t)], axis=1).astype(np.float32)


def _linear(rng, d_in, d_out, bias=True):
    k1, _ = jax.random.split(rng)
    p = {"w": jax.random.normal(k1, (d_in, d_out)) * (d_in ** -0.5)}
    if bias:
        p["b"] = jnp.zeros((d_out,))
    return p


def _attn_params(rng, d, bias=True):
    ks = jax.random.split(rng, 4)
    return {"q": _linear(ks[0], d, d), "k": _linear(ks[1], d, d, bias=False),
            "v": _linear(ks[2], d, d), "o": _linear(ks[3], d, d)}


def _block_params(rng, d, cross: bool):
    ks = jax.random.split(rng, 5)
    p = {"attn": _attn_params(ks[0], d),
         "attn_ln": {"w": jnp.ones((d,)), "b": jnp.zeros((d,))},
         "fc1": _linear(ks[1], d, 4 * d), "fc2": _linear(ks[2], 4 * d, d),
         "mlp_ln": {"w": jnp.ones((d,)), "b": jnp.zeros((d,))}}
    if cross:
        p["xattn"] = _attn_params(ks[3], d)
        p["xattn_ln"] = {"w": jnp.ones((d,)), "b": jnp.zeros((d,))}
    return p


def init_params(rng: jax.Array, cfg: WhisperConfig) -> Params:
    ks = jax.random.split(rng, 8 + cfg.enc_layers + cfg.dec_layers)
    d = cfg.d_model
    params: Params = {
        "conv1_w": jax.random.normal(ks[0], (d, cfg.n_mels, 3)) * 0.05,
        "conv1_b": jnp.zeros((d,)),
        "conv2_w": jax.random.normal(ks[1], (d, d, 3)) * 0.05,
        "conv2_b": jnp.zeros((d,)),
        "enc_pos": jnp.asarray(_sinusoids(cfg.n_audio_ctx, d)),
        "enc_ln": {"w": jnp.ones((d,)), "b": jnp.zeros((d,))},
        "tok_embed": jax.random.normal(ks[2], (cfg.vocab_size, d)) * 0.02,
        "dec_pos": jax.random.normal(ks[3], (cfg.n_text_ctx, d)) * 0.01,
        "dec_ln": {"w": jnp.ones((d,)), "b": jnp.zeros((d,))},
        "enc_blocks": [_block_params(ks[8 + i], d, cross=False)
                       for i in range(cfg.enc_layers)],
        "dec_blocks": [_block_params(ks[8 + cfg.enc_layers + i], d,
                                     cross=True)
                       for i in range(cfg.dec_layers)],
    }
    return params


def _ln(x, p):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5) * p["w"] + p["b"]


def _lin(x, p):
    y = x @ p["w"]
    return y + p["b"] if "b" in p else y


def _mha(q_in, kv_in, p, cfg: WhisperConfig, causal: bool):
    B, S, D = q_in.shape
    T = kv_in.shape[1]
    H, HD = cfg.n_heads, cfg.head_dim
    q = _lin(q_in, p["q"]).reshape(B, S, H, HD) * (HD ** -0.5)
    k = _lin(kv_in, p["k"]).reshape(B, T, H, HD)
    v = _lin(kv_in, p["v"]).reshape(B, T, H, HD)
    scores = jnp.einsum("bshd,bthd->bhst", q, k)
    if causal:
        mask = jnp.tril(jnp.ones((S, T), bool), T - S)
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    ctx = jnp.einsum("bhst,bthd->bshd", jax.nn.softmax(scores, -1), v)
    return _lin(ctx.reshape(B, S, D), p["o"])


def _block(h, p, cfg, causal, enc_out=None):
    h = h + _mha(_ln(h, p["attn_ln"]), _ln(h, p["attn_ln"]), p["attn"],
                 cfg, causal)
    if enc_out is not None:
        h = h + _mha(_ln(h, p["xattn_ln"]), enc_out, p["xattn"], cfg, False)
    x = _ln(h, p["mlp_ln"])
    return h + _lin(jax.nn.gelu(_lin(x, p["fc1"]), approximate=False),
                    p["fc2"])


def encode(params: Params, cfg: WhisperConfig, mel: jnp.ndarray
           ) -> jnp.ndarray:
    """mel (B, n_mels, n_audio_frames) → encoder states (B, n_audio_ctx, D)."""
    dn = ("NCH", "OIH", "NCH")
    h = jax.lax.conv_general_dilated(mel, params["conv1_w"], (1,),
                                     [(1, 1)], dimension_numbers=dn)
    h = jax.nn.gelu(h + params["conv1_b"][None, :, None], approximate=False)
    h = jax.lax.conv_general_dilated(h, params["conv2_w"], (2,),
                                     [(1, 1)], dimension_numbers=dn)
    h = jax.nn.gelu(h + params["conv2_b"][None, :, None], approximate=False)
    h = h.transpose(0, 2, 1) + params["enc_pos"][None]
    for blk in params["enc_blocks"]:
        h = _block(h, blk, cfg, causal=False)
    return _ln(h, params["enc_ln"])


def decode_logits(params: Params, cfg: WhisperConfig, tokens: jnp.ndarray,
                  enc_out: jnp.ndarray) -> jnp.ndarray:
    """tokens (B, S) + encoder states → logits (B, S, vocab)."""
    S = tokens.shape[1]
    h = params["tok_embed"][tokens] + params["dec_pos"][None, :S]
    for blk in params["dec_blocks"]:
        h = _block(h, blk, cfg, causal=True, enc_out=enc_out)
    h = _ln(h, params["dec_ln"])
    return h @ params["tok_embed"].T


def _xattn_kv(params: Params, cfg: WhisperConfig, enc_out: jnp.ndarray):
    """Per-block cross-attention K/V over the encoder states — computed
    once per utterance, reused by every decode step."""
    # HF whisper cross-attention projects the RAW encoder states (the
    # xattn_ln norms the DECODER hidden, applied to q in the step)
    return [(_lin(enc_out, blk["xattn"]["k"]),
             _lin(enc_out, blk["xattn"]["v"]))
            for blk in params["dec_blocks"]]


def _decode_step_cached(params, cfg: WhisperConfig, tok, pos, self_kv,
                        cross_kv):
    """One cached greedy-decode step: tok (B,), pos scalar, self_kv a list
    of per-block (k, v) with shape (B, n_text_ctx, D); returns (logits
    (B, V), self_kv'). Attention masks keys past ``pos``."""
    B = tok.shape[0]
    D, H, HD = cfg.d_model, cfg.n_heads, cfg.head_dim
    h = params["tok_embed"][tok] + params["dec_pos"][pos]      # (B, D)
    h = h[:, None]                                             # (B, 1, D)
    new_kv = []
    key_mask = (jnp.arange(cfg.n_text_ctx) <= pos)[None, None, None, :]
    for blk, (ck, cv), (sk, sv) in zip(params["dec_blocks"], cross_kv,
                                       self_kv):
        x = _ln(h, blk["attn_ln"])
        p = blk["attn"]
        q = _lin(x, p["q"]).reshape(B, 1, H, HD) * (HD ** -0.5)
        sk = jax.lax.dynamic_update_slice_in_dim(sk, _lin(x, p["k"]), pos, 1)
        sv = jax.lax.dynamic_update_slice_in_dim(sv, _lin(x, p["v"]), pos, 1)
        new_kv.append((sk, sv))
        k = sk.reshape(B, cfg.n_text_ctx, H, HD)
        v = sv.reshape(B, cfg.n_text_ctx, H, HD)
        scores = jnp.einsum("bshd,bthd->bhst", q, k)
        scores = jnp.where(key_mask, scores, -jnp.inf)
        ctx = jnp.einsum("bhst,bthd->bshd", jax.nn.softmax(scores, -1), v)
        h = h + _lin(ctx.reshape(B, 1, D), p["o"])
        # cross attention over the precomputed encoder K/V
        x = _ln(h, blk["xattn_ln"])
        p = blk["xattn"]
        q = _lin(x, p["q"]).reshape(B, 1, H, HD) * (HD ** -0.5)
        Te = ck.shape[1]
        kx = ck.reshape(B, Te, H, HD)
        vx = cv.reshape(B, Te, H, HD)
        scores = jnp.einsum("bshd,bthd->bhst", q, kx)
        ctx = jnp.einsum("bhst,bthd->bshd", jax.nn.softmax(scores, -1), vx)
        h = h + _lin(ctx.reshape(B, 1, D), p["o"])
        x = _ln(h, blk["mlp_ln"])
        h = h + _lin(jax.nn.gelu(_lin(x, blk["fc1"]), approximate=False),
                     blk["fc2"])
    h = _ln(h, params["dec_ln"])
    return (h @ params["tok_embed"].T)[:, 0], new_kv


_step_cached_jit = jax.jit(
    lambda params, cfg, tok, pos, self_kv, cross_kv: _decode_step_cached(
        params, cfg, tok, pos, self_kv, cross_kv),
    static_argnums=1, donate_argnums=(4,))   # cache updates in place


def transcribe_ids(params: Params, cfg: WhisperConfig, audio: np.ndarray,
                   max_tokens: int = 128) -> List[int]:
    """Greedy transcription token ids (specials stripped). Decodes over a
    per-block self-attention KV cache (one fixed-shape step program, O(n)
    per utterance) with the cross-attention K/V precomputed once."""
    mel = jnp.asarray(log_mel(audio, cfg))[None]
    enc_out = _encode_jit(params, cfg, mel)
    cross_kv = _xattn_kv(params, cfg, enc_out)
    self_kv = [(jnp.zeros((1, cfg.n_text_ctx, cfg.d_model)),
                jnp.zeros((1, cfg.n_text_ctx, cfg.d_model)))
               for _ in params["dec_blocks"]]
    prompt = [cfg.sot, cfg.lang_en, cfg.task_transcribe, cfg.no_timestamps]
    ids = list(prompt)
    max_len = min(cfg.n_text_ctx, len(prompt) + max_tokens)
    for pos in range(max_len):
        feeding = pos < len(prompt) - 1
        if not feeding and len(ids) >= max_len:
            break                        # a further step's token is unusable
        logits, self_kv = _step_cached_jit(
            params, cfg, jnp.asarray([ids[pos]], jnp.int32),
            pos, self_kv, cross_kv)
        if feeding:
            continue                     # still feeding the prompt
        nxt = int(jnp.argmax(logits[0]))
        if nxt == cfg.eot:
            break
        ids.append(nxt)
    return ids[len(prompt):]


# module-level jitted entry point (per-call jax.jit would recompile every
# call); cfg is a frozen dataclass → hashable static arg. Full-forward
# decode_logits stays unjitted — it is the parity/reference path only.
_encode_jit = jax.jit(lambda params, cfg, mel: encode(params, cfg, mel),
                      static_argnums=1)


# ---------------------------------------------------------------------------
# HuggingFace checkpoint import (openai/whisper-* layout)
# ---------------------------------------------------------------------------

def params_from_hf(state_dict, cfg: WhisperConfig) -> Params:
    """Map a transformers WhisperForConditionalGeneration state_dict onto
    the params tree (weights transposed to x@W layout). Works for any
    whisper size whose dims match ``cfg``."""
    sd = {k: np.asarray(v) for k, v in state_dict.items()}

    def lin(prefix):
        p = {"w": sd[f"{prefix}.weight"].T}
        if f"{prefix}.bias" in sd:
            p["b"] = sd[f"{prefix}.bias"]
        return p

    def ln(prefix):
        return {"w": sd[f"{prefix}.weight"], "b": sd[f"{prefix}.bias"]}

    def attn(prefix):
        return {"q": lin(f"{prefix}.q_proj"), "k": lin(f"{prefix}.k_proj"),
                "v": lin(f"{prefix}.v_proj"), "o": lin(f"{prefix}.out_proj")}

    def block(prefix, cross):
        p = {"attn": attn(f"{prefix}.self_attn"),
             "attn_ln": ln(f"{prefix}.self_attn_layer_norm"),
             "fc1": lin(f"{prefix}.fc1"), "fc2": lin(f"{prefix}.fc2"),
             "mlp_ln": ln(f"{prefix}.final_layer_norm")}
        if cross:
            p["xattn"] = attn(f"{prefix}.encoder_attn")
            p["xattn_ln"] = ln(f"{prefix}.encoder_attn_layer_norm")
        return p

    enc, dec = "model.encoder", "model.decoder"
    params: Params = {
        "conv1_w": sd[f"{enc}.conv1.weight"],
        "conv1_b": sd[f"{enc}.conv1.bias"],
        "conv2_w": sd[f"{enc}.conv2.weight"],
        "conv2_b": sd[f"{enc}.conv2.bias"],
        "enc_pos": sd[f"{enc}.embed_positions.weight"][: cfg.n_audio_ctx],
        "enc_ln": ln(f"{enc}.layer_norm"),
        "tok_embed": sd[f"{dec}.embed_tokens.weight"],
        "dec_pos": sd[f"{dec}.embed_positions.weight"],
        "dec_ln": ln(f"{dec}.layer_norm"),
        "enc_blocks": [block(f"{enc}.layers.{i}", cross=False)
                       for i in range(cfg.enc_layers)],
        "dec_blocks": [block(f"{dec}.layers.{i}", cross=True)
                       for i in range(cfg.dec_layers)],
    }
    return jax.tree.map(jnp.asarray, params)
