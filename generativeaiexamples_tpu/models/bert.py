"""BERT-family encoder — bi-encoder embeddings + cross-encoder reranking.

The in-tree replacement for the models inside the reference's NeMo Retriever
NIM containers: the `nv-embedqa-e5-v5` passage/query embedder
(ref: RAG/examples/local_deploy/docker-compose-nim-ms.yaml:30-56, client
utils.py:407-446) and the `nv-rerankqa-mistral-4b-v3` cross-encoder reranker
(ref: docker-compose-nim-ms.yaml:58-81, client utils.py:448-471).

Architecture: standard pre-LN-free BERT encoder (post-LN, learned positions,
GELU) so HF `BertModel` checkpoints (e5-class bi-encoders are BERT-backboned)
load directly; parity-tested against transformers like the Llama decoder.

TPU-first shape: layers stacked + `lax.scan`; bidirectional attention is one
fused einsum per block (no flash needed at e5 sequence lengths — 512 tokens
fits VMEM-friendly tiles); logical sharding axes match the decoder so the
same mesh rules apply. Pooling variants: mean (e5 convention), CLS.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    hidden_dim: int = 3072
    max_positions: int = 512
    type_vocab_size: int = 2
    norm_eps: float = 1e-12
    dtype: str = "float32"
    pooling: str = "mean"  # "mean" (e5) | "cls" (rerank head input)

    @staticmethod
    def e5_base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def tiny(vocab_size: int = 300) -> "BertConfig":
        return BertConfig(vocab_size=vocab_size, dim=32, n_layers=2, n_heads=2,
                          hidden_dim=64, max_positions=128)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def jdtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


def init_params(rng: jax.Array, cfg: BertConfig,
                with_rank_head: bool = False) -> Params:
    L, D, F = cfg.n_layers, cfg.dim, cfg.hidden_dim
    keys = jax.random.split(rng, 12)
    dt = cfg.jdtype

    def normal(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)).astype(dt)

    params: Params = {
        "tok_embed": normal(keys[0], (cfg.vocab_size, D), D),
        "pos_embed": normal(keys[1], (cfg.max_positions, D), D),
        "type_embed": normal(keys[2], (cfg.type_vocab_size, D), D),
        "embed_norm": {"scale": jnp.ones((D,), dt), "bias": jnp.zeros((D,), dt)},
        "layers": {
            "wq": normal(keys[3], (L, D, D), D),
            "bq": jnp.zeros((L, D), dt),
            "wk": normal(keys[4], (L, D, D), D),
            "bk": jnp.zeros((L, D), dt),
            "wv": normal(keys[5], (L, D, D), D),
            "bv": jnp.zeros((L, D), dt),
            "wo": normal(keys[6], (L, D, D), D),
            "bo": jnp.zeros((L, D), dt),
            "attn_norm": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
            "w_up": normal(keys[7], (L, D, F), D),
            "b_up": jnp.zeros((L, F), dt),
            "w_down": normal(keys[8], (L, F, D), F),
            "b_down": jnp.zeros((L, D), dt),
            "mlp_norm": {"scale": jnp.ones((L, D), dt), "bias": jnp.zeros((L, D), dt)},
        },
    }
    if with_rank_head:
        # cross-encoder relevance head on pooled output → scalar score
        params["rank_head"] = {"w": normal(keys[9], (D, 1), D),
                               "b": jnp.zeros((1,), dt)}
    return params


def logical_axes(cfg: BertConfig, with_rank_head: bool = False) -> Params:
    def norm_ax(layered: bool):
        lead = (None,) if layered else ()
        return {"scale": lead + ("embed",), "bias": lead + ("embed",)}

    ax: Params = {
        "tok_embed": ("vocab_table", "embed_table"),
        "pos_embed": (None, "embed_table"),
        "type_embed": (None, "embed_table"),
        "embed_norm": norm_ax(False),
        "layers": {
            "wq": (None, "embed", "heads"), "bq": (None, "heads"),
            "wk": (None, "embed", "heads"), "bk": (None, "heads"),
            "wv": (None, "embed", "heads"), "bv": (None, "heads"),
            "wo": (None, "heads", "embed"), "bo": (None, "embed"),
            "attn_norm": norm_ax(True),
            "w_up": (None, "embed", "mlp"), "b_up": (None, "mlp"),
            "w_down": (None, "mlp", "embed"), "b_down": (None, "embed"),
            "mlp_norm": norm_ax(True),
        },
    }
    if with_rank_head:
        ax["rank_head"] = {"w": ("embed", None), "b": (None,)}
    return ax


def _layer_norm(x: jnp.ndarray, scale: jnp.ndarray, bias: jnp.ndarray,
                eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (out * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def _gelu(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.gelu(x.astype(jnp.float32), approximate=False).astype(x.dtype)


def encode(params: Params, cfg: BertConfig, tokens: jnp.ndarray,
           attn_mask: Optional[jnp.ndarray] = None,
           token_types: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """tokens (B, S) → contextual embeddings (B, S, D)."""
    B, S = tokens.shape
    if attn_mask is None:
        attn_mask = jnp.ones((B, S), bool)
    if token_types is None:
        token_types = jnp.zeros((B, S), jnp.int32)
    h = (params["tok_embed"][tokens]
         + params["pos_embed"][jnp.arange(S)][None]
         + params["type_embed"][token_types]).astype(cfg.jdtype)
    h = _layer_norm(h, params["embed_norm"]["scale"], params["embed_norm"]["bias"],
                    cfg.norm_eps)
    H, HD = cfg.n_heads, cfg.head_dim
    mask = attn_mask[:, None, None, :]  # (B, 1, 1, S)
    scale = 1.0 / math.sqrt(HD)

    def body(h, layer):
        q = (h @ layer["wq"] + layer["bq"]).reshape(B, S, H, HD)
        k = (h @ layer["wk"] + layer["bk"]).reshape(B, S, H, HD)
        v = (h @ layer["wv"] + layer["bv"]).reshape(B, S, H, HD)
        scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                            k.astype(jnp.float32)) * scale
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("bhst,bthd->bshd", probs, v.astype(jnp.float32))
        ctx = ctx.reshape(B, S, H * HD).astype(h.dtype)
        h = _layer_norm(h + ctx @ layer["wo"] + layer["bo"],
                        layer["attn_norm"]["scale"], layer["attn_norm"]["bias"],
                        cfg.norm_eps)
        up = _gelu(h @ layer["w_up"] + layer["b_up"])
        h = _layer_norm(h + up @ layer["w_down"] + layer["b_down"],
                        layer["mlp_norm"]["scale"], layer["mlp_norm"]["bias"],
                        cfg.norm_eps)
        return h, None

    h, _ = jax.lax.scan(body, h, params["layers"])
    return h


def embed(params: Params, cfg: BertConfig, tokens: jnp.ndarray,
          attn_mask: Optional[jnp.ndarray] = None,
          normalize: bool = True) -> jnp.ndarray:
    """Sentence embeddings (B, D): masked-mean pooling (e5) or CLS."""
    B, S = tokens.shape
    if attn_mask is None:
        attn_mask = jnp.ones((B, S), bool)
    h = encode(params, cfg, tokens, attn_mask)
    if cfg.pooling == "cls":
        pooled = h[:, 0]
    else:
        m = attn_mask[..., None].astype(jnp.float32)
        pooled = (h.astype(jnp.float32) * m).sum(axis=1) / jnp.maximum(m.sum(axis=1), 1.0)
    if normalize:
        pooled = pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True).clip(1e-9)
    return pooled.astype(jnp.float32)


def rank_score(params: Params, cfg: BertConfig, tokens: jnp.ndarray,
               attn_mask: Optional[jnp.ndarray] = None,
               token_types: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Cross-encoder relevance: (query ⊕ passage) pairs (B, S) → scores (B,).

    Pairs are packed as [CLS] query [SEP] passage [SEP] with token_type 1 on
    the passage segment (BERT pair convention); score = rank_head(CLS)."""
    h = encode(params, cfg, tokens, attn_mask, token_types)
    cls = h[:, 0].astype(jnp.float32)
    head = params["rank_head"]
    return (cls @ head["w"].astype(jnp.float32) + head["b"])[:, 0]


# ---------------------------------------------------------------------------
# HuggingFace import (BertModel state_dict)
# ---------------------------------------------------------------------------

def params_from_hf(state_dict: Dict[str, Any], cfg: BertConfig,
                   prefix: str = "") -> Params:
    """Map HF `BertModel.state_dict()` into this layout (parity tests + local
    e5 checkpoints). `prefix` handles nesting (e.g. 'bert.')."""
    import numpy as np

    def t(name):
        w = state_dict[prefix + name]
        arr = w.detach().cpu().numpy() if hasattr(w, "detach") else np.asarray(w)
        return jnp.asarray(arr, cfg.jdtype)

    def lin(name):
        return t(name).T

    L = cfg.n_layers
    stacks: Dict[str, list] = {k: [] for k in (
        "wq", "bq", "wk", "bk", "wv", "bv", "wo", "bo",
        "attn_scale", "attn_bias", "w_up", "b_up", "w_down", "b_down",
        "mlp_scale", "mlp_bias")}
    for i in range(L):
        p = f"encoder.layer.{i}."
        stacks["wq"].append(lin(p + "attention.self.query.weight"))
        stacks["bq"].append(t(p + "attention.self.query.bias"))
        stacks["wk"].append(lin(p + "attention.self.key.weight"))
        stacks["bk"].append(t(p + "attention.self.key.bias"))
        stacks["wv"].append(lin(p + "attention.self.value.weight"))
        stacks["bv"].append(t(p + "attention.self.value.bias"))
        stacks["wo"].append(lin(p + "attention.output.dense.weight"))
        stacks["bo"].append(t(p + "attention.output.dense.bias"))
        stacks["attn_scale"].append(t(p + "attention.output.LayerNorm.weight"))
        stacks["attn_bias"].append(t(p + "attention.output.LayerNorm.bias"))
        stacks["w_up"].append(lin(p + "intermediate.dense.weight"))
        stacks["b_up"].append(t(p + "intermediate.dense.bias"))
        stacks["w_down"].append(lin(p + "output.dense.weight"))
        stacks["b_down"].append(t(p + "output.dense.bias"))
        stacks["mlp_scale"].append(t(p + "output.LayerNorm.weight"))
        stacks["mlp_bias"].append(t(p + "output.LayerNorm.bias"))

    stack = lambda k: jnp.stack(stacks[k])
    return {
        "tok_embed": t("embeddings.word_embeddings.weight"),
        "pos_embed": t("embeddings.position_embeddings.weight"),
        "type_embed": t("embeddings.token_type_embeddings.weight"),
        "embed_norm": {"scale": t("embeddings.LayerNorm.weight"),
                       "bias": t("embeddings.LayerNorm.bias")},
        "layers": {
            "wq": stack("wq"), "bq": stack("bq"),
            "wk": stack("wk"), "bk": stack("bk"),
            "wv": stack("wv"), "bv": stack("bv"),
            "wo": stack("wo"), "bo": stack("bo"),
            "attn_norm": {"scale": stack("attn_scale"), "bias": stack("attn_bias")},
            "w_up": stack("w_up"), "b_up": stack("b_up"),
            "w_down": stack("w_down"), "b_down": stack("b_down"),
            "mlp_norm": {"scale": stack("mlp_scale"), "bias": stack("mlp_bias")},
        },
    }
