"""Serve a local HuggingFace Llama checkpoint directory directly.

The reference's NIM serves real Llama checkpoints out of a model
directory (docs/support-matrix.md:17-19); the in-tree equivalent loads a
local HF-format directory — ``config.json`` + ``*.safetensors`` (+
``tokenizer.json``, picked up separately by engine/tokenizer.py) — maps
it through :func:`models.llama.params_from_hf`, and derives the
:class:`LlamaConfig` from the HF config, so
``APP_ENGINE_CHECKPOINT_DIR=/path/to/hf-llama`` serves real weights with
no conversion step. Zero torch on the load path: safetensors reads
straight into numpy.
"""

from __future__ import annotations

import json
import os
from glob import glob
from typing import Tuple

from generativeaiexamples_tpu.models import llama


def is_hf_dir(directory: str) -> bool:
    return (os.path.isfile(os.path.join(directory, "config.json"))
            and bool(glob(os.path.join(directory, "*.safetensors"))))


def config_from_hf(directory: str) -> llama.LlamaConfig:
    """LlamaConfig from an HF ``config.json`` (llama/llama3 families)."""
    with open(os.path.join(directory, "config.json"), encoding="utf-8") as fh:
        hc = json.load(fh)
    arch = (hc.get("architectures") or ["LlamaForCausalLM"])[0]
    if "Llama" not in arch:
        raise ValueError(f"unsupported HF architecture {arch!r} "
                         "(llama-family checkpoints only)")
    n_heads = int(hc["num_attention_heads"])
    head_dim = int(hc.get("head_dim")
                   or hc["hidden_size"] // n_heads)
    return llama.LlamaConfig(
        vocab_size=int(hc["vocab_size"]),
        dim=int(hc["hidden_size"]),
        n_layers=int(hc["num_hidden_layers"]),
        n_heads=n_heads,
        n_kv_heads=int(hc.get("num_key_value_heads", n_heads)),
        hidden_dim=int(hc["intermediate_size"]),
        head_dim=head_dim,
        rope_theta=float(hc.get("rope_theta", 500000.0)),
        norm_eps=float(hc.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hc.get("tie_word_embeddings", False)),
        dtype="bfloat16",
    )


def load_hf_dir(directory: str) -> Tuple[llama.LlamaConfig, llama.Params]:
    """(config, params) from a local HF Llama directory — safetensors →
    numpy → :func:`llama.params_from_hf` (which owns the layout mapping
    and the HF-parity guarantees the test suite pins)."""
    from safetensors.numpy import load_file

    cfg = config_from_hf(directory)
    state = {}
    for shard in sorted(glob(os.path.join(directory, "*.safetensors"))):
        state.update(load_file(shard))
    params = llama.params_from_hf(state, cfg)
    return cfg, params
