"""Serve a local HuggingFace Llama checkpoint directory directly.

The reference's NIM serves real Llama checkpoints out of a model
directory (docs/support-matrix.md:17-19); the in-tree equivalent loads a
local HF-format directory — ``config.json`` + ``*.safetensors`` (+
``tokenizer.json``, picked up separately by engine/tokenizer.py) — maps
it through :func:`models.llama.params_from_hf`, and derives the
:class:`LlamaConfig` from the HF config, so
``APP_ENGINE_CHECKPOINT_DIR=/path/to/hf-llama`` serves real weights with
no conversion step. Zero torch on the load path: safetensors reads
straight into numpy.
"""

from __future__ import annotations

import json
import os
from glob import glob
from typing import Tuple

from generativeaiexamples_tpu.models import llama


def is_hf_dir(directory: str) -> bool:
    return (os.path.isfile(os.path.join(directory, "config.json"))
            and bool(glob(os.path.join(directory, "*.safetensors"))))


def config_from_hf(directory: str) -> llama.LlamaConfig:
    """LlamaConfig from an HF ``config.json`` (llama/llama3 families)."""
    with open(os.path.join(directory, "config.json"), encoding="utf-8") as fh:
        hc = json.load(fh)
    arch = (hc.get("architectures") or ["LlamaForCausalLM"])[0]
    if "Llama" not in arch:
        raise ValueError(f"unsupported HF architecture {arch!r} "
                         "(llama-family checkpoints only)")
    n_heads = int(hc["num_attention_heads"])
    head_dim = int(hc.get("head_dim")
                   or hc["hidden_size"] // n_heads)
    rope_scaling = _parse_rope_scaling(hc.get("rope_scaling"))
    return llama.LlamaConfig(
        vocab_size=int(hc["vocab_size"]),
        dim=int(hc["hidden_size"]),
        n_layers=int(hc["num_hidden_layers"]),
        n_heads=n_heads,
        n_kv_heads=int(hc.get("num_key_value_heads", n_heads)),
        hidden_dim=int(hc["intermediate_size"]),
        head_dim=head_dim,
        rope_theta=float(hc.get("rope_theta", 500000.0)),
        rope_scaling=rope_scaling,
        norm_eps=float(hc.get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(hc.get("tie_word_embeddings", False)),
        dtype="bfloat16",
    )


def _parse_rope_scaling(block) -> "tuple | None":
    """HF ``rope_scaling`` → the LlamaConfig tuple, or a LOUD failure.

    Llama-3.1/3.2 checkpoints ship ``rope_type: "llama3"`` (rescale
    low-frequency RoPE components at all positions — ops/layers.py
    rotary_embedding applies it); serving such a checkpoint while ignoring
    the block would produce silently wrong positional encodings, so any
    rope_scaling this loader does not implement raises instead of
    degrading."""
    if not block:
        return None
    rope_type = str(block.get("rope_type") or block.get("type") or "")
    if rope_type in ("default", "none"):
        return None
    if rope_type == "llama3":
        try:
            return (float(block["factor"]),
                    float(block["low_freq_factor"]),
                    float(block["high_freq_factor"]),
                    int(block["original_max_position_embeddings"]))
        except KeyError as exc:
            raise ValueError(
                f"rope_scaling of type 'llama3' is missing field {exc}; "
                f"got {sorted(block)}") from exc
    raise ValueError(
        f"unsupported rope_scaling type {rope_type!r} in config.json — "
        "implemented: 'llama3' (Llama-3.1/3.2), 'default'. Serving this "
        "checkpoint without its scaling rule would silently corrupt "
        "positional encodings.")


def load_hf_dir(directory: str) -> Tuple[llama.LlamaConfig, llama.Params]:
    """(config, params) from a local HF Llama directory — safetensors →
    numpy → :func:`llama.params_from_hf` (which owns the layout mapping
    and the HF-parity guarantees the test suite pins)."""
    from safetensors.numpy import load_file

    cfg = config_from_hf(directory)
    state = {}
    for shard in sorted(glob(os.path.join(directory, "*.safetensors"))):
        state.update(load_file(shard))
    params = llama.params_from_hf(state, cfg)
    return cfg, params
