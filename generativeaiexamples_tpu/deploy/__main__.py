"""CLI: bring up the full stack with one command (compose-parity dev loop).

    python -m generativeaiexamples_tpu.deploy up [--tiny] \
        [--chain-port 8081] [--ui-port 8090]

Starts the chain server (in-proc TPU engine + encoders) and, once it
reports healthy, the playground UI against it — the reference's
`docker compose up` flow (ref basic_rag/langchain/docker-compose.yaml)
without containers. Ctrl-C tears the stack down in reverse order. Crashed
services restart with backoff (supervisor monitor)."""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import time

from generativeaiexamples_tpu.deploy.supervisor import ServiceSpec, Supervisor


def build_stack(tiny: bool, chain_port: int, ui_port: int):
    py = sys.executable
    chain_cmd = [py, "-m", "generativeaiexamples_tpu.server",
                 "--port", str(chain_port)]
    if tiny:
        chain_cmd.append("--tiny")
    return [
        ServiceSpec(
            name="chain-server",
            command=chain_cmd,
            health_url=f"http://127.0.0.1:{chain_port}/health",
            startup_timeout_s=600.0,      # first TPU compile is slow
        ),
        ServiceSpec(
            name="playground",
            command=[py, "-m", "generativeaiexamples_tpu.playground",
                     "--chain-url", f"http://127.0.0.1:{chain_port}",
                     "--port", str(ui_port)],
            health_url=f"http://127.0.0.1:{ui_port}/health",
            depends_on=["chain-server"],
        ),
    ]


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("action", choices=["up"],
                        help="bring the stack up (runs in the foreground)")
    parser.add_argument("--tiny", action="store_true",
                        help="tiny deterministic model (dev/test)")
    parser.add_argument("--chain-port", type=int, default=8081)
    parser.add_argument("--ui-port", type=int, default=8090)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")

    sup = Supervisor(build_stack(args.tiny, args.chain_port, args.ui_port))
    stop = {"flag": False}

    def handle(sig, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    try:
        # inside the try: a failed bring-up (health timeout, early exit of a
        # later service) must still tear down the services already started
        sup.up()
        logging.info("stack up: chain http://127.0.0.1:%d  "
                     "ui http://127.0.0.1:%d (Ctrl-C to stop)",
                     args.chain_port, args.ui_port)
        while not stop["flag"]:
            time.sleep(1)
    finally:
        sup.down()


if __name__ == "__main__":
    main()
