"""Deployment: one-command multi-service bring-up with health gating.

The reference deploys as docker-compose stacks whose services gate on each
other's health (ref: RAG/examples/basic_rag/langchain/docker-compose.yaml:
59-64 `depends_on: condition: service_healthy`) and restart on failure.
This package is the native equivalent for TPU hosts: a process supervisor
(`supervisor.Supervisor`) that starts services in dependency order, admits
each only after its /health endpoint answers, restarts crashed services
with exponential backoff, and tears the stack down in reverse order —
plus the stock stack definition (chain server → playground) behind
``python -m generativeaiexamples_tpu.deploy up``.
"""

from generativeaiexamples_tpu.deploy.supervisor import (  # noqa: F401
    ServiceSpec, Supervisor)
