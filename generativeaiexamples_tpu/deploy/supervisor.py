"""Process supervisor: health-gated startup, crash restart, ordered teardown.

Native counterpart of the reference's compose semantics
(ref docker-compose.yaml:59-64 `depends_on: service_healthy`, restart
policies) — the failure-detection/recovery layer SURVEY §5.3 calls for:

  * **health-gated ordering** — a service starts only after everything in
    its ``depends_on`` reports healthy (HTTP /health 200), so the chain
    server never races its engine, the UI never races the chain server;
  * **failure detection** — the monitor thread polls both process liveness
    (exit code) and the health endpoint; either failing marks the service
    down;
  * **recovery** — crashed services restart with FULL-JITTER exponential
    backoff (server/resilience.py, the one backoff implementation every
    retry loop shares): a stack of services crashing together restarts
    spread out instead of as a synchronized herd hammering the same
    port/device at the same instant. Restarts count into
    ``supervisor_restarts_total{service}``. Dependents simply keep
    running: the per-request failure path is handled inside each service
    — e.g. the scheduler fails streams loudly and keeps serving,
    engine/scheduler.py;
  * **ordered teardown** — reverse dependency order, SIGTERM then SIGKILL.
"""

from __future__ import annotations

import logging
import os
import signal
import subprocess
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.server.resilience import full_jitter_backoff

logger = logging.getLogger(__name__)


@dataclass
class ServiceSpec:
    name: str
    command: List[str]
    health_url: str = ""                 # empty = liveness-only (no probe)
    depends_on: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    startup_timeout_s: float = 120.0
    restart: bool = True
    max_restarts: int = 5


@dataclass
class _ServiceState:
    spec: ServiceSpec
    proc: Optional[subprocess.Popen] = None
    healthy: bool = False
    restarts: int = 0
    backoff_until: float = 0.0
    # a death has been noticed and its restart scheduled at backoff_until;
    # the spawn happens on a LATER monitor pass (the jitter must be real —
    # spawning in the same pass would restart a crashed stack as the
    # synchronized herd the jitter exists to break up)
    pending_restart: bool = False


def _http_ok(url: str, timeout: float = 2.0) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return 200 <= resp.status < 300
    except Exception as exc:
        # a down process is this probe's normal negative result — debug
        # keeps restart loops quiet but traceable
        logger.debug("health probe %s failed: %s", url, exc)
        return False


class Supervisor:
    """Owns a stack of ServiceSpecs for its lifetime."""

    def __init__(self, services: Sequence[ServiceSpec],
                 poll_interval_s: float = 1.0) -> None:
        self._order = self._toposort(services)
        self._states = {s.name: _ServiceState(spec=s) for s in services}
        self.poll_interval_s = poll_interval_s
        self._running = False
        self._monitor: Optional[threading.Thread] = None

    @staticmethod
    def _toposort(services: Sequence[ServiceSpec]) -> List[ServiceSpec]:
        by_name = {s.name: s for s in services}
        seen: Dict[str, int] = {}          # 0 = visiting, 1 = done
        order: List[ServiceSpec] = []

        def visit(name: str) -> None:
            if seen.get(name) == 1:
                return
            if seen.get(name) == 0:
                raise ValueError(f"dependency cycle through {name!r}")
            if name not in by_name:
                raise ValueError(f"unknown dependency {name!r}")
            seen[name] = 0
            for dep in by_name[name].depends_on:
                visit(dep)
            seen[name] = 1
            order.append(by_name[name])

        for s in services:
            visit(s.name)
        return order

    # ------------------------------------------------------------ lifecycle

    def _spawn(self, st: _ServiceState) -> None:
        env = {**os.environ, **st.spec.env}
        logger.info("starting %s: %s", st.spec.name,
                    " ".join(st.spec.command))
        st.proc = subprocess.Popen(st.spec.command, env=env,
                                   start_new_session=True)
        st.healthy = not st.spec.health_url   # liveness-only = healthy-ish

    def _wait_healthy(self, st: _ServiceState) -> None:
        if not st.spec.health_url:
            return
        deadline = time.monotonic() + st.spec.startup_timeout_s
        while time.monotonic() < deadline:
            if st.proc.poll() is not None:
                raise RuntimeError(
                    f"{st.spec.name} exited (rc={st.proc.returncode}) "
                    f"before becoming healthy")
            if _http_ok(st.spec.health_url):
                st.healthy = True
                logger.info("%s healthy at %s", st.spec.name,
                            st.spec.health_url)
                return
            time.sleep(self.poll_interval_s)
        raise RuntimeError(f"{st.spec.name} failed health check at "
                           f"{st.spec.health_url} within "
                           f"{st.spec.startup_timeout_s}s")

    def up(self) -> None:
        """Start every service in dependency order, gating on health."""
        self._running = True
        for spec in self._order:
            st = self._states[spec.name]
            self._spawn(st)
            self._wait_healthy(st)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="deploy-monitor", daemon=True)
        self._monitor.start()

    def down(self) -> None:
        """Reverse-order teardown: SIGTERM, then SIGKILL stragglers."""
        self._running = False
        if self._monitor:
            self._monitor.join(timeout=10)
        for spec in reversed(self._order):
            st = self._states[spec.name]
            if st.proc and st.proc.poll() is None:
                logger.info("stopping %s", spec.name)
                st.proc.terminate()
        deadline = time.monotonic() + 15
        for spec in reversed(self._order):
            st = self._states[spec.name]
            if not st.proc:
                continue
            try:
                st.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                logger.warning("killing %s", spec.name)
                st.proc.kill()

    def status(self) -> Dict[str, Dict[str, object]]:
        out = {}
        for name, st in self._states.items():
            alive = bool(st.proc and st.proc.poll() is None)
            out[name] = {"alive": alive,
                         "healthy": alive and st.healthy,
                         "restarts": st.restarts,
                         "pid": st.proc.pid if st.proc else None}
        return out

    # -------------------------------------------------------------- monitor

    def _monitor_loop(self) -> None:
        while self._running:
            for spec in self._order:
                st = self._states[spec.name]
                if not self._running:
                    return
                alive = st.proc and st.proc.poll() is None
                if alive and st.spec.health_url:
                    st.healthy = _http_ok(st.spec.health_url)
                if alive:
                    continue
                st.healthy = False
                if not st.spec.restart:
                    continue
                now = time.monotonic()
                if not st.pending_restart:
                    if st.restarts >= st.spec.max_restarts:
                        logger.error("%s exceeded %d restarts; giving up",
                                     spec.name, spec.max_restarts)
                        continue
                    st.restarts += 1
                    # full jitter (server/resilience.py): uniform in
                    # [0, min(60, 2^restarts)] — the old deterministic
                    # min(2**restarts, 60) restarted a crashed stack as a
                    # synchronized herd (every service's next attempt
                    # landed on the same instant, re-colliding on
                    # ports/device). The spawn waits for backoff_until on
                    # a later pass, so the jitter actually spaces the herd.
                    delay = full_jitter_backoff(st.restarts + 1, base_s=1.0,
                                                cap_s=60.0)
                    st.backoff_until = now + delay
                    st.pending_restart = True
                    REGISTRY.counter("supervisor_restarts_total",
                                     labels={"service": spec.name}).inc()
                    logger.warning("%s died (rc=%s); restart %d/%d in %.1fs",
                                   spec.name,
                                   st.proc.returncode if st.proc else "?",
                                   st.restarts, spec.max_restarts, delay)
                if now < st.backoff_until:
                    continue
                st.pending_restart = False
                try:
                    self._spawn(st)
                except Exception:
                    logger.exception("restart of %s failed", spec.name)
            time.sleep(self.poll_interval_s)
