"""Latency forensics plane — "where did the time go for THIS request".

The trace plane (observability/trace.py) records every scheduling
decision; the flight recorder keeps the gauges; the SLO judge stamps a
verdict on every finished request. This module is the layer that
*interprets* those streams:

* ``FORENSICS.breakdown(rid)`` reconstructs the critical path of one
  request as an ordered, cause-tagged segment list. Segments partition
  the ``[submit, finish]`` interval on the trace mono axis exactly — a
  cursor walks the request's boundary events, so the segment durations
  sum to the end-to-end latency by construction, not by luck.
* ``FORENSICS.observe(req)`` (scheduler finish paths, guarded by
  ``FORENSICS.enabled`` — one attribute read when ``APP_FORENSICS=off``,
  the APP_TRACE/APP_DEVTIME zero-overhead pattern) auto-captures the
  FULL trace slice + breakdown for requests that breached their SLO or
  landed above the trailing p99, into a bounded exemplar ring. The
  interesting requests survive ring eviction; the boring ones age out.
* ``doctor_payload()`` maps active symptoms (recompiles, padding waste,
  spill thrash, qos sheds, affinity overrides, retry-budget exhaustion,
  watchdog trips, lock inversions) to named causes ranked by estimated
  device-seconds lost, each naming the ``docs/configuration.md`` knob
  to turn.

Served at ``GET /debug/forensics[/<rid>]`` and ``GET /debug/doctor``
(server/common.py); cross-worker requests are joined on the router from
per-leg breakdowns (the usage-plane /health piggyback pattern). This
module imports no jax and is safe in router/encoder processes.

Clock discipline: all time reads go through core/clock.py (the tpulint
clock-injection rule covers this module) so simulated runs produce
simulated forensics.
"""

from __future__ import annotations

import bisect
import os
from collections import OrderedDict, deque
from typing import Any, Deque, Dict, List, Optional

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import alerts as alerts_mod
from generativeaiexamples_tpu.observability import flight as flight_mod
from generativeaiexamples_tpu.observability.lockwatch import tracked_lock
from generativeaiexamples_tpu.observability.trace import TRACE

# Segment cause vocabulary (docs/observability.md "Why was this request
# slow"). Bounded set — these appear as JSON fields, never metric labels.
CAUSE_QOS = "qos_throttle"
CAUSE_PREEMPT = "page_pressure_preempt"
CAUSE_SPILL_PROMOTE = "spill_promote"
CAUSE_TIER_PROMOTE = "tier_promote"
CAUSE_RECOMPILE = "recompile_hazard"
CAUSE_HEDGE_LOSER = "hedge_loser"

_DEF_CAPACITY = 64
_P99_RESERVOIR = 512
_P99_MIN_SAMPLES = 30


def _env_mode() -> str:
    return (os.environ.get("APP_FORENSICS", "").strip().lower() or "off")


def _seg(label: str, t0: float, t1: float, cause: str = "",
         **extra: Any) -> Dict[str, Any]:
    seg = {"label": label, "t0_s": round(t0, 6),
           "dur_s": round(max(0.0, t1 - t0), 6), "cause": cause}
    seg.update(extra)
    return seg


def trace_slice(rid: str, records: Optional[List[dict]] = None) -> List[dict]:
    """All trace records about one request, oldest-first.

    Joins rid-stamped events with the GLOBAL dispatch emits (one per
    device program, not per request) via their ``rids`` roster field —
    the per-request prefill/decode boundaries live there.
    """
    if not rid:
        return []
    out = []
    for rec in (TRACE.records() if records is None else records):
        if rec.get("rid") == rid:
            out.append(rec)
            continue
        roster = rec.get("rids")
        if roster and rid in str(roster).split(","):
            out.append(rec)
    out.sort(key=lambda r: (r.get("mono", 0.0), r.get("seq", 0)))
    return out


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


class _Builder:
    """Cursor state machine: walks one request's boundary events and
    closes a segment at each transition, so segments partition
    ``[start, end]`` exactly."""

    def __init__(self, start: float) -> None:
        self.cursor = start
        self.segments: List[Dict[str, Any]] = []
        self.label = "queue_wait"
        self.cause = ""
        self.pending_cause = ""      # promote annotates the NEXT close
        self.prefill_chunks = 0
        self.decode = None           # aggregate decode segment, open
        self.decode_last = 0.0
        self.decode_dispatches = 0
        self.decode_max_gap = 0.0

    def close(self, t: float, **extra: Any) -> None:
        cause = self.cause or self.pending_cause
        self.pending_cause = ""
        if t > self.cursor or not self.segments:
            self.segments.append(
                _seg(self.label, self.cursor, t, cause, **extra))
            self.cursor = t
        elif extra or cause:
            # zero-width transition: fold annotations into the last seg
            last = self.segments[-1]
            if cause and not last.get("cause"):
                last["cause"] = cause
            last.update(extra)

    def open(self, label: str, cause: str = "") -> None:
        self.label, self.cause = label, cause

    def close_decode(self, t: float) -> None:
        if self.decode is None:
            return
        self.segments.append(_seg(
            "decode", self.decode, t, self.cause,
            dispatches=self.decode_dispatches,
            max_gap_s=round(self.decode_max_gap, 6)))
        self.cursor = t
        self.decode = None
        self.cause = ""


def build_breakdown(rid: str,
                    records: Optional[List[dict]] = None) -> Dict[str, Any]:
    """Ordered, cause-tagged segment breakdown for one request.

    Prefers the trace stream (per-chunk resolution); falls back to the
    REQUEST_LOG coarse timeline when the trace has no events for the rid
    (ring evicted, or APP_TRACE was off). Returns ``{"found": False}``
    when neither plane knows the request.
    """
    events = trace_slice(rid, records)
    if events:
        bd = _breakdown_from_trace(rid, events)
        if bd is not None:
            return bd
    return _breakdown_from_timeline(rid)


def _breakdown_from_trace(rid: str,
                          events: List[dict]) -> Optional[Dict[str, Any]]:
    start = end = None
    meta: Dict[str, Any] = {}
    for ev in events:
        k = ev.get("kind")
        if k == "submit" and start is None:
            start = float(ev.get("mono", 0.0))
            for f in ("prompt_tokens", "max_tokens", "slo", "tenant",
                      "handoff", "est_cost_s"):
                if ev.get(f) not in (None, ""):
                    meta[f] = ev[f]
        elif k == "finish":
            end = float(ev.get("mono", 0.0))
            meta["finish"] = ev.get("finish", "")
            if ev.get("error"):
                meta["error"] = ev["error"]
        elif k == "migrate":
            end = float(ev.get("mono", 0.0))
            meta.setdefault("finish", "evacuated")
        elif k == "qos" and ev.get("decision") == "shed":
            end = float(ev.get("mono", 0.0))
            meta["finish"] = "shed"
    if start is None and events and events[0].get("kind") == "router_leg":
        return _breakdown_from_router_legs(rid, events)
    if start is None:
        return None
    if end is None:
        end = float(events[-1].get("mono", start))
        meta.setdefault("finish", "inflight")
    b = _Builder(start)
    for ev in events:
        t = float(ev.get("mono", 0.0))
        if t < start or t > end:
            continue
        k = ev.get("kind")
        if k == "qos" and ev.get("decision") == "shed":
            b.cause = CAUSE_QOS
            b.close(t, reason=str(ev.get("reason", "")))
            b.open("shed", CAUSE_QOS)
        elif k == "admit":
            b.close_decode(t)
            b.close(t)
            b.open("admission")
        elif k == "promote":
            b.pending_cause = (CAUSE_SPILL_PROMOTE
                               if ev.get("source") == "spill"
                               else CAUSE_TIER_PROMOTE)
        elif k == "dispatch":
            phase = ev.get("phase", "")
            if phase in ("prefill", "prefill_long"):
                b.close_decode(t)
                b.close(t)
                b.prefill_chunks += 1
                b.open("prefill_chunk")
            elif phase == "decode":
                if b.decode is None:
                    b.close(t)
                    b.decode = b.cursor
                    b.decode_last = t
                    b.decode_dispatches = 1
                else:
                    b.decode_max_gap = max(b.decode_max_gap,
                                           t - b.decode_last)
                    b.decode_last = t
                    b.decode_dispatches += 1
        elif k == "preempt":
            b.close_decode(t)
            b.cause = b.cause or CAUSE_PREEMPT
            b.close(t, mode=str(ev.get("mode", "")))
            b.open("preempt_wait", CAUSE_PREEMPT)
        elif k == "spill":
            b.close_decode(t)
            b.cause = b.cause or CAUSE_PREEMPT
            b.close(t)
            b.open("spill_wait", CAUSE_PREEMPT)
        elif k == "router_leg":
            # router-axis legs ride along in joined payloads; they do not
            # partition the engine axis
            continue
    if b.decode is not None:
        b.close_decode(end)
    elif b.cursor < end or not b.segments:
        b.close(end)
    _annotate_recompiles(b.segments, start, end)
    total = round(sum(s["dur_s"] for s in b.segments), 6)
    return {"found": True, "rid": rid, "source": "trace",
            "start_mono": round(start, 6), "end_mono": round(end, 6),
            "e2e_s": round(end - start, 6), "segments_total_s": total,
            "segments": b.segments, "meta": meta, "events": len(events)}


def _breakdown_from_router_legs(rid: str,
                                events: List[dict]) -> Dict[str, Any]:
    """Router-axis breakdown: partition [first leg start, last leg end]
    from ``router_leg`` events (each stamped at leg END with its
    duration). Gaps between legs become ``router_gap`` segments, so the
    partition stays exact on the router's own clock."""
    legs = [ev for ev in events if ev.get("kind") == "router_leg"]
    if not legs:
        return {"found": False, "rid": rid}
    bounds = []
    for ev in legs:
        t1 = float(ev.get("mono", 0.0))
        bounds.append((t1 - float(ev.get("dur_s", 0.0) or 0.0), t1, ev))
    start = min(b[0] for b in bounds)
    end = max(b[1] for b in bounds)
    segments: List[Dict[str, Any]] = []
    cursor = start
    meta: Dict[str, Any] = {"axis": "router"}
    for t0, t1, ev in sorted(bounds, key=lambda b: b[1]):
        t0 = max(t0, cursor)
        if t0 > cursor:
            segments.append(_seg("router_gap", cursor, t0))
            cursor = t0
        cause = ""
        if ev.get("hedge_loser"):
            cause = CAUSE_HEDGE_LOSER
        extra = {k: ev[k] for k in ("worker", "hedged", "tokens")
                 if ev.get(k) not in (None, "")}
        if t1 > cursor or not segments:
            segments.append(_seg("router_" + str(ev.get("leg", "leg")),
                                 cursor, t1, cause, **extra))
            cursor = t1
        if ev.get("mode"):
            meta["mode"] = ev["mode"]
    total = round(sum(s["dur_s"] for s in segments), 6)
    return {"found": True, "rid": rid, "source": "router_legs",
            "start_mono": round(start, 6), "end_mono": round(end, 6),
            "e2e_s": round(end - start, 6), "segments_total_s": total,
            "segments": segments, "meta": meta, "events": len(legs)}


def _annotate_recompiles(segments: List[Dict[str, Any]], start: float,
                         end: float) -> None:
    """Mid-serving XLA compiles overlapping the request window tag the
    overlapped segment ``recompile_hazard`` — the flight recorder stamps
    each compile with the same mono clock the trace uses."""
    try:
        compiles = [ev for ev in flight_mod.FLIGHT.events(seconds=86400.0)
                    if ev.get("event") == "recompile"
                    and start <= float(ev.get("mono", -1.0)) <= end]
    except Exception:   # tpulint: disable=except-swallow -- annotation pass only: a malformed flight event must never kill a breakdown
        return
    if not compiles:
        return
    starts = [s["t0_s"] for s in segments]
    for ev in compiles:
        i = max(0, bisect.bisect_right(starts, float(ev["mono"])) - 1)
        seg = segments[i]
        if not seg.get("cause"):
            seg["cause"] = CAUSE_RECOMPILE
        seg["recompiles"] = int(seg.get("recompiles", 0)) + 1


def _breakdown_from_timeline(rid: str) -> Dict[str, Any]:
    """Coarse fallback off REQUEST_LOG perf stamps: queue → admission →
    prefill → decode/stream. Partitions [queued, finished] exactly on
    the perf axis."""
    rec = flight_mod.REQUEST_LOG.get(rid)
    if not rec:
        return {"found": False, "rid": rid}
    ph = rec.get("phases", {}) or {}
    queued = ph.get("queued")
    finished = ph.get("finished")
    if queued is None or finished is None:
        return {"found": False, "rid": rid, "partial": rec}
    marks = [("queue_wait", queued),
             ("admission", ph.get("admitted")),
             ("prefill", ph.get("prefill_start")),
             ("decode_stream", ph.get("first_token"))]
    segments: List[Dict[str, Any]] = []
    cursor = float(queued)
    label = "queue_wait"
    for nxt_label, t in marks[1:] + [("end", finished)]:
        if t is None:
            continue
        t = float(t)
        if t > cursor or not segments:
            cause = ""
            if label == "queue_wait" and rec.get("preemptions"):
                cause = ""
            segments.append(_seg(label, cursor, t, cause))
            cursor = t
        label = nxt_label
    if rec.get("preemptions"):
        for seg in segments:
            if seg["label"] in ("prefill", "decode_stream"):
                seg.setdefault("cause", "")
        segments[-1]["preemptions"] = rec["preemptions"]
    total = round(sum(s["dur_s"] for s in segments), 6)
    meta = {k: rec.get(k) for k in ("finish", "error", "tenant", "slo_class",
                                    "prompt_tokens", "completion_tokens",
                                    "preemptions", "spill_resumes")
            if rec.get(k) not in (None, "", 0)}
    return {"found": True, "rid": rid, "source": "timeline",
            "e2e_s": round(float(finished) - float(queued), 6),
            "segments_total_s": total, "segments": segments, "meta": meta,
            "durations_s": rec.get("durations_s", {})}


class ForensicsPlane:
    """Bounded tail-exemplar ring + breakdown service (process-global
    ``FORENSICS``). ``enabled`` follows APP_FORENSICS=off|on; every hot
    call site guards on it, so off-mode costs one attribute read."""

    def __init__(self) -> None:
        self.enabled = _env_mode() in ("on", "1", "true")
        cap = int(os.environ.get("APP_FORENSICS_CAPACITY", "")
                  or _DEF_CAPACITY)
        self.capacity = max(4, cap)
        self._lock = tracked_lock("forensics._lock")
        self._ring: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._e2e: Deque[float] = deque(maxlen=_P99_RESERVOIR)

    # -- configuration ---------------------------------------------------

    def configure(self, mode: Optional[str] = None,
                  capacity: Optional[int] = None) -> None:
        """Runtime re-arm (bench rounds, tests). Turning forensics on
        also arms the trace plane — breakdowns are built from its
        events."""
        if mode is not None:
            self.enabled = mode.strip().lower() in ("on", "1", "true")
            if self.enabled and not TRACE.enabled:
                TRACE.configure(mode="on")
        if capacity is not None:
            with self._lock:
                self.capacity = max(4, int(capacity))
                while len(self._ring) > self.capacity:
                    self._ring.popitem(last=False)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._e2e.clear()

    # -- capture (scheduler finish paths) --------------------------------

    def observe(self, req: Any) -> None:
        """Finish-path hook. Callers guard with ``if FORENSICS.enabled``;
        here we judge capture-worthiness: SLO breach/error/shed, or e2e
        above the trailing p99 once the reservoir has warmed up."""
        if not self.enabled:
            return
        rid = str(getattr(req, "request_id", "") or "")
        verdict = getattr(req, "slo", None) or {}
        alerts_mod.ALERTS.observe(req, verdict)
        e2e = float(verdict.get("e2e_s") or 0.0)
        reason = ""
        outcome = verdict.get("outcome", "")
        if outcome in ("breached", "error"):
            reason = "error" if outcome == "error" else "breach"
        elif outcome == "shed":
            reason = "shed"
        elif e2e > 0.0:
            with self._lock:
                vals = sorted(self._e2e)
            if len(vals) >= _P99_MIN_SAMPLES and \
                    e2e >= _percentile(vals, 0.99):
                reason = "tail"
        with self._lock:
            if e2e > 0.0:
                self._e2e.append(e2e)
        if not reason or not rid:
            return
        self.capture(rid, reason, verdict)

    def capture(self, rid: str, reason: str,
                verdict: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Retain the FULL trace slice + breakdown for one request."""
        events = trace_slice(rid)
        exemplar = {
            "rid": rid, "reason": reason,
            "captured_unix": round(clock.wall(), 3),
            "verdict": dict(verdict or {}),
            "breakdown": build_breakdown(rid, events or None),
            "trace": events,
        }
        with self._lock:
            self._ring[rid] = exemplar
            self._ring.move_to_end(rid)
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
        REGISTRY.counter("forensics_exemplars_total",
                         labels={"reason": reason}).inc()
        return exemplar

    # -- read surface ----------------------------------------------------

    def get(self, rid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            ex = self._ring.get(rid)
            return dict(ex) if ex else None

    def exemplars(self) -> List[Dict[str, Any]]:
        """Newest-first listing (without the trace payloads)."""
        with self._lock:
            rows = list(self._ring.values())
        out = []
        for ex in reversed(rows):
            bd = ex.get("breakdown") or {}
            out.append({"rid": ex["rid"], "reason": ex["reason"],
                        "captured_unix": ex["captured_unix"],
                        "e2e_s": bd.get("e2e_s"),
                        "outcome": (ex.get("verdict") or {}).get("outcome"),
                        "segments": len(bd.get("segments", []) or []),
                        "trace_events": len(ex.get("trace", []) or [])})
        return out

    def top_exemplars(self, n: int = 3) -> List[Dict[str, Any]]:
        """The n slowest captured exemplars (bench round JSON): breakdown
        + verdict, trace slice omitted to keep round lines greppable."""
        with self._lock:
            rows = list(self._ring.values())
        rows.sort(key=lambda ex: float(
            (ex.get("breakdown") or {}).get("e2e_s") or 0.0), reverse=True)
        return [{"rid": ex["rid"], "reason": ex["reason"],
                 "verdict": ex.get("verdict"),
                 "breakdown": ex.get("breakdown")}
                for ex in rows[:max(0, int(n))]]

    def payload(self, rid: str) -> Dict[str, Any]:
        """GET /debug/forensics/<rid> body: captured exemplar when one
        exists, else a live breakdown from whatever the planes still
        hold."""
        ex = self.get(rid)
        if ex is not None:
            return {"enabled": self.enabled, "captured": True, **ex}
        bd = build_breakdown(rid)
        return {"enabled": self.enabled, "captured": False, "rid": rid,
                "breakdown": bd, "trace": trace_slice(rid)}

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._ring)
            samples = len(self._e2e)
            vals = sorted(self._e2e)
        return {"enabled": self.enabled,
                "mode": "on" if self.enabled else "off",
                "capacity": self.capacity, "captured": n,
                "p99_samples": samples,
                "trailing_p99_s": round(_percentile(vals, 0.99), 6)
                if len(vals) >= _P99_MIN_SAMPLES else None}


FORENSICS = ForensicsPlane()


# --------------------------------------------------------------- doctor

def _family_sum(name: str) -> float:
    try:
        return float(sum(REGISTRY.family(name).values()))
    except Exception:   # tpulint: disable=except-swallow -- a missing family reads as zero symptoms; the doctor stays total
        return 0.0


def _family_rows(name: str) -> Dict[str, float]:
    """Labeled counter family flattened to 'k=v,k=v' → value."""
    try:
        fam = REGISTRY.family(name)
    except Exception:   # tpulint: disable=except-swallow -- same contract as _family_sum: absent evidence, not an error
        return {}
    return {",".join(f"{k}={v}" for k, v in key): val
            for key, val in fam.items()}


def _perf_model() -> Any:
    from generativeaiexamples_tpu.observability.devtime import DEVTIME
    return DEVTIME.perf()


def _prefill_cost_s(tokens: float) -> float:
    perf = _perf_model()
    if perf is not None:
        try:
            est = perf.prefill_seconds(tokens)
            if est:
                return float(est)
        except Exception:   # tpulint: disable=except-swallow -- a perf model without chip peaks falls back to the documented constant
            pass
    return 2e-5 * tokens          # FakeCore fallback (tests, no model)


def doctor_payload() -> Dict[str, Any]:
    """GET /debug/doctor body: active symptoms → named causes, ranked by
    estimated device-seconds lost (core/perfmodel.py where a model is
    attached, documented fallbacks otherwise). Each diagnosis names the
    docs/configuration.md knob to turn. Safe in every process — engine
    surfaces are read through sys.modules, never imported."""
    import sys

    from generativeaiexamples_tpu.observability.devtime import DEVTIME
    from generativeaiexamples_tpu.observability.lockwatch import WATCH

    diagnoses: List[Dict[str, Any]] = []

    def add(cause: str, symptom: str, lost_s: float, knob: str,
            severity: str = "warn", **evidence: Any) -> None:
        diagnoses.append({
            "cause": cause, "symptom": symptom,
            "est_device_s_lost": round(max(0.0, lost_s), 6),
            "knob": knob, "severity": severity, "evidence": evidence})

    # recompiles: each mid-serving XLA compile stalls live requests for
    # roughly its compile time; without a measured figure we charge 1 s
    # per event (XLA compiles are seconds, not milliseconds)
    comp = DEVTIME.compiles()
    recompiles = int(comp.get("recompiles_total", 0))
    if recompiles:
        add("recompile_hazard",
            f"{recompiles} mid-serving XLA compile(s) — shape buckets "
            "were never warmed",
            recompiles * 1.0,
            "warm all APP_ENGINE_DECODE_WIDTH_LADDER / "
            "APP_ENGINE_PREFILL_CHUNK buckets at startup; see "
            "GET /debug/compiles",
            severity="critical",
            recompiles_total=recompiles,
            programs=sorted({e.get("program", "") for e in
                             comp.get("events", [])})[:8])

    # padding waste: fraction of attributed device time spent on pad rows
    waste = float(DEVTIME.padding_waste() or 0.0)
    attributed = float(DEVTIME.attributed_s() or 0.0)
    if waste > 0.05 and attributed > 0.0:
        add("padding_waste",
            f"{waste:.0%} of attributed device time is padding",
            waste * attributed,
            "tighten APP_ENGINE_DECODE_WIDTH_LADDER rungs or lower "
            "APP_ENGINE_PREFILL_CHUNK",
            padding_waste_frac=round(waste, 4),
            attributed_s=round(attributed, 3))

    # spill / preemption thrash: every recompute-preempt re-prefills the
    # prompt; every spill resume pays host<->device wire
    preemptions = _family_sum("preemptions")
    spills = _family_sum("kv_spill_total")
    spill_resumes = _family_sum("spill_resumes")
    if preemptions or spills:
        # recomputed prompt work ~ preemptions * mean prompt; without the
        # per-request figure, charge one 512-token re-prefill each
        lost = preemptions * _prefill_cost_s(512.0)
        add("page_pressure",
            f"{int(preemptions)} preemption(s), {int(spills)} spill(s), "
            f"{int(spill_resumes)} spill resume(s) — KV page pool too "
            "small for the working set",
            lost + 0.01 * spill_resumes,
            "raise APP_ENGINE_NUM_PAGES or APP_ENGINE_KV_SPILL_MB; "
            "consider APP_ENGINE_KV_TIER=prefix for returning prefixes",
            severity="critical" if preemptions > 10 else "warn",
            preemptions=int(preemptions), kv_spill_total=int(spills),
            spill_resumes=int(spill_resumes))

    # qos sheds: admission control is refusing work
    sheds = (_family_sum("slo_shed_total")
             + _family_sum("qos_shed_before_prefill_total"))
    if sheds:
        add("qos_shed",
            f"{int(sheds)} request(s) shed at admission",
            0.0,
            "raise tenant quotas (APP_ENGINE_QOS_QUOTA) or add replicas; "
            "sheds protect goodput, so first check slo_pressure",
            sheds=int(sheds),
            by_class=_family_rows("slo_shed_total"))

    # router affinity overrides: sticky placement losing to load
    aff = _family_rows("router_affinity_total")
    overrides = sum(v for k, v in aff.items() if "override" in k)
    if overrides:
        add("affinity_override",
            f"{int(overrides)} prefix-affinity override(s) — sticky "
            "workers were too loaded to honor KV reuse",
            overrides * _prefill_cost_s(256.0),
            "raise APP_ROUTER_AFFINITY_SLACK or add decode replicas",
            affinity=aff)

    # retry budget exhaustion: failover is out of headroom
    denied = (_family_sum("retries_denied_total")
              + _family_sum("retry_budget_exhausted_total"))
    if denied:
        add("retry_budget",
            f"{int(denied)} retry(ies) denied — failover budget "
            "exhausted, failures are surfacing to callers",
            0.0,
            "raise APP_ROUTER_RETRY_BUDGET only after fixing the "
            "underlying worker churn (see /debug/fleet)",
            severity="critical", retries_denied=int(denied))

    # watchdog trips: the driver stalled past its deadline
    trips = _family_sum("engine_watchdog_trips_total")
    if trips:
        add("watchdog_trip",
            f"{int(trips)} watchdog trip(s) — driver ticks stalled",
            0.0,
            "inspect GET /debug/stacks; raise APP_ENGINE_WATCHDOG_S only "
            "if ticks are legitimately that long",
            severity="critical", trips=int(trips))

    # lock inversions (when the lockwatch sanitizer is armed)
    try:
        inversions = list(WATCH.inversions)
    except Exception:   # tpulint: disable=except-swallow -- an unarmed/mid-reset lockwatch is simply no evidence
        inversions = []
    if inversions:
        add("lock_inversion",
            f"{len(inversions)} lock-order inversion(s) witnessed",
            0.0,
            "fix the acquisition order (docs/static_analysis.md); "
            "APP_LOCKWATCH=on reproduces",
            severity="critical",
            edges=[i.get("cycle") or i for i in inversions[:4]])

    # qos live pressure (engine process only — sys.modules, never import)
    qos_mod = sys.modules.get("generativeaiexamples_tpu.engine.qos")
    qos_state = None
    if qos_mod is not None:
        try:
            qos_state = qos_mod.debug_payload()
        except Exception:   # tpulint: disable=except-swallow -- a mid-registration policy answers null, never breaks the doctor
            qos_state = None

    diagnoses.sort(key=lambda d: (d["severity"] != "critical",
                                  -d["est_device_s_lost"]))
    from generativeaiexamples_tpu.observability import slo as slo_mod
    return {
        "healthy": not diagnoses,
        "diagnoses": diagnoses,
        "alerts": alerts_mod.ALERTS.active(),
        "slo_pressure": slo_mod.SLO.pressure(),
        "forensics": FORENSICS.describe(),
        "qos": qos_state,
        "generated_unix": round(clock.wall(), 3),
    }
