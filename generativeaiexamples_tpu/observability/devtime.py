"""Device-time attribution plane: which program burned the chip, live.

The bench could always compute MFU after the fact, but the serving engine
itself could not say which program FAMILY the time went to, whether a
mid-serving XLA recompile caused a latency cliff, or what a KV handoff
costs per request — RAGO (arxiv 2503.14649) frames serving optimization as
a search that is only navigable with exactly this per-phase attribution.
One process-global ledger (``DEVTIME``), three layers:

  * **Dispatch ledger.** Every device program the engine issues is
    classified into a ``(program, bucket)`` key that mirrors the XLA
    compile unit — ``decode[+gram][+top] / s<steps>``, ``mixed / g<G>s<K>``,
    ``prefill / g<G>``, ``prefill_long / n<len>``, ``kv_export / p<pages>``,
    ``kv_import / p<pages>``, encoder micro-batches ``embed|rerank /
    b<batch>`` — and accumulates count, device/queue/issue seconds, useful
    vs padded token rows, and weight-read passes. Served as
    ``engine_device_seconds{program,bucket}`` plus live ``engine_mfu
    {program}`` / ``engine_hbm_read_util`` gauges (formulas from
    core/perfmodel.py — the same arithmetic bench.py reports) and the
    ``GET /debug/devtime`` breakdown.

  * **Sampling gate.** ``APP_DEVTIME`` = ``off`` (default: counts only,
    ZERO added device fences — test-enforced) | ``sample`` (one timing
    fence every ``APP_DEVTIME_SAMPLE_N``-th commit; device seconds
    extrapolated by the stride) | ``on`` (fence every dispatch — full
    attribution for bench/debug; it serializes the dispatch pipeline, so
    never the serving default). Every fence routes through :func:`_fence`
    — the tpulint ``devtime-fence`` rule flags any other bare
    ``jax.block_until_ready`` so instrumentation cannot quietly become the
    bottleneck it measures. A timed commit splits its wall into
    ``queue_s`` (draining work queued ahead) vs ``device_s`` (this
    program) using the previous dispatch's output as the drain marker.

  * **Compile-watch.** :meth:`DevtimeLedger.mark_warm` records every key
    ``EngineCore.warmup`` compiled; :meth:`mark_serving` closes that
    window (Scheduler.start). A key first seen AFTER serving started that
    warmup never compiled is a mid-serving XLA recompile: counted into
    ``engine_recompiles_total``, recorded as a flight-recorder event, and
    (when timing is enabled) raised as a ``recompile`` hazard through the
    PR 4 SLO pressure plane — the classic TPU latency cliff becomes an
    alert instead of a mystery p99. ``GET /debug/compiles`` lists every
    compile event with its trigger key; first-call vs steady-state timing
    per key corroborates when sampling is on.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability.flight import FLIGHT

logger = logging.getLogger(__name__)

_MODES = ("off", "sample", "on")
_WINDOW = 256          # trailing timed samples per program for live gauges
_COMPILE_LOG = 256     # bounded compile-event history


def _env_mode() -> Tuple[str, int]:
    """(mode, sample_n) from the environment: the bare ``APP_DEVTIME``
    wins, else the config-documented ``APP_ENGINE_DEVTIME``
    (core/config.py EngineConfig.devtime), else off."""
    raw = (os.environ.get("APP_DEVTIME", "").strip().lower()
           or os.environ.get("APP_ENGINE_DEVTIME", "").strip().lower()
           or "off")
    if raw not in _MODES:
        logger.warning("APP_DEVTIME=%r is not off|sample|on; using off", raw)
        raw = "off"
    try:
        n = int(os.environ.get("APP_DEVTIME_SAMPLE_N", "") or 16)
    except ValueError:
        n = 16
    return raw, max(1, n)


def pow2_bucket(n: int, start: int = 1) -> int:
    """Smallest power-of-two multiple of ``start`` covering ``n`` — THE
    bucket function every ledger key derives from (kv page counts, encoder
    batch sizes, long-prefill lengths). One copy, so committing sites and
    warm-key marking can never fork the key space."""
    b = max(1, start)
    while b < n:
        b *= 2
    return b


def _fence(arrays: Any) -> None:
    """The ONE device fence the ledger ever takes — the sampling gate's
    enforcement point (tests monkeypatch this to prove ``off`` adds zero
    fences; tpulint's devtime-fence rule flags fences that bypass it).
    ``jax.block_until_ready`` passes host (numpy) arrays through untouched,
    so FakeCore scheduler tests exercise the identical code path."""
    import jax
    jax.block_until_ready(arrays)   # tpulint: disable=devtime-fence -- this IS the sampled ledger fence every other call site must route through


class _Entry:
    """Accumulator for one (program, bucket) ledger key."""

    __slots__ = ("program", "bucket", "count", "timed", "device_s", "queue_s",
                 "issue_s", "tokens", "padded_tokens", "timed_tokens",
                 "weight_passes", "first_seen_unix", "first_timed_s",
                 "last_timed_mono", "window", "pending_timed")

    def __init__(self, program: str, bucket: str) -> None:
        self.program = program
        self.bucket = bucket
        self.count = 0
        self.timed = 0
        self.device_s = 0.0
        self.queue_s = 0.0
        self.issue_s = 0.0         # host time to issue the async dispatch
        self.tokens = 0.0          # useful token positions processed
        self.padded_tokens = 0.0   # positions the program actually padded to
        self.timed_tokens = 0.0    # tokens of the TIMED dispatches only
        self.weight_passes = 0.0   # full weight-set HBM reads
        self.first_seen_unix = time.time()
        self.first_timed_s: Optional[float] = None
        self.last_timed_mono: Optional[float] = None
        # trailing timed (tokens, device_s, weight_passes) for live gauges
        self.window: deque = deque(maxlen=_WINDOW)
        # TIMED defer_census commits awaiting their note_tokens() census —
        # their device_s is already in; pairing the deferred tokens back
        # keeps phase_rates' device-seconds-per-token honest
        self.pending_timed = 0

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "program": self.program, "bucket": self.bucket,
            "count": self.count, "timed": self.timed,
            "device_s": round(self.device_s, 6),
            "queue_s": round(self.queue_s, 6),
            "issue_s": round(self.issue_s, 6),
            "tokens": int(self.tokens),
            "padded_tokens": int(self.padded_tokens),
            "weight_passes": round(self.weight_passes, 2),
            "row_util": (round(self.tokens / self.padded_tokens, 4)
                         if self.padded_tokens else None),
            "first_seen_unix": round(self.first_seen_unix, 3),
        }
        if self.timed:
            # sampled mode times 1/N of the dispatches: the estimate scales
            # the timed seconds by the observed count ratio (uniformity
            # assumption, stated in docs/observability.md)
            out["est_device_s"] = round(
                self.device_s * self.count / self.timed, 6)
            out["first_timed_s"] = (round(self.first_timed_s, 6)
                                    if self.first_timed_s is not None
                                    else None)
            steady = sorted(d for _, d, _ in self.window)
            out["steady_p50_s"] = (round(steady[len(steady) // 2], 6)
                                   if steady else None)
        return out


class DevtimeLedger:
    """Process-global dispatch ledger + compile-watch (see module doc).

    Thread-safety: commits arrive from the engine driver thread, encoder
    micro-batch workers, and bench threads; one lock guards the maps, and
    the (optional) fence always runs OUTSIDE it so a slow device sync never
    serializes other committers.
    """

    def __init__(self, mode: Optional[str] = None,
                 sample_n: Optional[int] = None) -> None:
        env_mode, env_n = _env_mode()
        self._mode = mode if mode in _MODES else env_mode
        self._sample_n = max(1, int(sample_n or env_n))
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], _Entry] = {}
        self._compiles: deque = deque(maxlen=_COMPILE_LOG)
        self._warm: set = set()
        self._serving = False
        self._commits = 0
        self._marker: Any = None          # previous dispatch's fence target
        self._perf = None                 # core.perfmodel.PerfModel
        # global trailing window of (weight_passes, device_s) for the
        # engine_hbm_read_util gauge (weight-bearing programs only)
        self._bw_window: deque = deque(maxlen=_WINDOW)
        # global trailing window of (useful, padded) token counts for the
        # engine_padding_waste_frac gauge — a CENSUS (every commit with a
        # padded count reports, no fence involved), so the gauge is live
        # even in the zero-fence off mode. Running totals keep the
        # per-commit cost O(1) (the window evicts at fixed maxlen) — the
        # off mode's counting-only cheapness must hold on the hot path.
        self._pad_window: deque = deque(maxlen=_WINDOW)
        self._pad_useful = 0.0
        self._pad_padded = 0.0
        # monotonic of the newest TIMED commit: consumers of the live
        # gauges (the usage plane's worker card) read the age to judge
        # staleness — gauges hold their last value while idle, they do
        # not decay
        self._last_timed_mono: Optional[float] = None
        # tests may redirect the recompile hazard away from the global SLO
        self.hazard_sink: Optional[Callable[[str, Dict[str, Any]], None]] = None
        # host-fetch accounting (multi-step decode plane): every device→host
        # result fetch counts into engine_host_fetches_total; fetches that
        # deliver decode steps also feed a trailing window whose mean is
        # the engine_steps_per_fetch gauge — THE observable the multi-step
        # decode ladder (EngineConfig.decode_multistep) exists to raise.
        # Census like the pad window: no fence, live even in off mode.
        self._fetch_window: deque = deque(maxlen=_WINDOW)
        self._fetch_steps = 0.0
        # the metric families exist (0-valued) from process start, so a
        # scrape before the first dispatch still sees the catalog
        REGISTRY.counter("engine_recompiles_total")
        REGISTRY.counter("engine_host_fetches_total")
        REGISTRY.gauge("engine_steps_per_fetch")
        REGISTRY.gauge("engine_hbm_read_util")
        REGISTRY.gauge("engine_padding_waste_frac")

    # ------------------------------------------------------------ lifecycle

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def timing_enabled(self) -> bool:
        return self._mode != "off"

    def configure(self, mode: Optional[str] = None,
                  sample_n: Optional[int] = None) -> None:
        """Runtime override (bench's attribution pass, tests)."""
        with self._lock:
            if mode is not None:
                if mode not in _MODES:
                    raise ValueError(f"devtime mode must be one of {_MODES}, "
                                     f"got {mode!r}")
                self._mode = mode
                if mode == "off":
                    self._marker = None   # drop the held buffer reference
            if sample_n is not None:
                self._sample_n = max(1, int(sample_n))

    def attach_perf(self, perf) -> None:
        """Install the analytic model (core/perfmodel.PerfModel) the live
        MFU/HBM gauges derive from; None detaches (gauges stop updating)."""
        with self._lock:
            self._perf = perf

    def perf(self):
        """The attached analytic model, or None — the forensics doctor
        costs symptoms in device-seconds through this."""
        with self._lock:
            return self._perf

    def mark_warm(self, program: str, bucket: Any) -> None:
        """Record that warmup compiled this key — its first dispatch is not
        a compile event (EngineCore.warmup calls this per compiled key)."""
        with self._lock:
            self._warm.add((program, str(bucket)))

    def mark_serving(self) -> None:
        """Close the warm window (Scheduler.start): keys first seen after
        this that warmup never compiled count as mid-serving recompiles."""
        with self._lock:
            self._serving = True

    def reset(self, keep_warm: bool = False) -> None:
        """Drop accumulated stats (tests, bench's attribution pass).
        ``keep_warm`` preserves the warm-key set and serving flag — AND
        folds every already-seen key into it (those programs are compiled
        in this process, whether warmup or a lazy first use compiled them)
        — so a stats reset can never re-announce an old compile as a fresh
        recompile."""
        with self._lock:
            if keep_warm:
                self._warm.update(self._entries.keys())
            self._entries.clear()
            self._compiles.clear()
            self._commits = 0
            self._marker = None
            self._bw_window.clear()
            self._pad_window.clear()
            self._pad_useful = 0.0
            self._pad_padded = 0.0
            self._fetch_window.clear()
            self._fetch_steps = 0.0
            self._last_timed_mono = None
            if not keep_warm:
                self._warm.clear()
                self._serving = False

    # --------------------------------------------------------------- commit

    def track(self) -> float:
        """Stamp taken immediately before issuing a dispatch; pass it to
        :meth:`commit` as ``t0`` so issue/queue/device time can split."""
        return time.perf_counter()

    def commit(self, program: str, bucket: Any, out: Any = None, *,
               t0: Optional[float] = None, tokens: float = 0,
               padded_tokens: float = 0, weight_passes: float = 0.0,
               device_s: Optional[float] = None, mfu: bool = True,
               retain: bool = True, defer_census: bool = False) -> None:
        """Account one issued device program.

        ``out`` is an output array (or pytree) of the dispatch — the fence
        target when this commit is sampled; with ``retain`` it also becomes
        the queue-drain marker for the next sampled commit (pass
        ``retain=False`` for buffers a later dispatch may donate away —
        fencing a deleted buffer raises). ``device_s`` short-circuits the
        gate for callers that already synced (kv export's copy-out, the
        encoder micro-batch whose dispatch blocks on results): the
        pre-measured duration is recorded with no extra fence in ANY mode.
        ``mfu=False`` keeps non-LLM programs (encoders, KV moves) out of
        the model-FLOP gauges — their tokens are not model forward passes.
        ``defer_census=True`` declares that this dispatch's useful-token
        census arrives later via :meth:`note_tokens` (multi-step decode:
        per-slot early exits are only known once the block is fetched);
        a TIMED deferred commit is remembered so the late tokens still
        pair with its device seconds — otherwise ``phase_rates`` would
        divide real device time by zero tokens and inflate the decode
        rate every downstream consumer (usage billing, the simulator's
        QoS costing) prorates with.
        """
        bucket = str(bucket)
        key = (program, bucket)
        t_commit = time.perf_counter()
        queue_s = 0.0
        # a pre-measured commit is a CENSUS (every occurrence reports), so
        # it must never be stride-extrapolated like a 1/N gate sample
        pre_measured = device_s is not None
        timed = pre_measured
        if not timed and out is not None and t0 is not None:
            with self._lock:
                if self._mode == "off":
                    due = False
                else:
                    self._commits += 1
                    due = (self._mode == "on"
                           or self._commits % self._sample_n == 0)
                marker = self._marker if due else None
                if self._mode != "off" and retain:
                    self._marker = out
            if due:
                if marker is not None:
                    try:
                        _fence(marker)
                    except Exception as exc:   # donated/deleted buffer
                        logger.debug("devtime queue marker unfencible: %s",
                                     exc)
                    queue_s = max(0.0, time.perf_counter() - t_commit)
                t_dev = time.perf_counter()
                _fence(out)
                device_s = time.perf_counter() - t_dev
                timed = True
        issue_s = max(0.0, t_commit - t0) if t0 is not None else 0.0
        with self._lock:
            entry = self._entries.get(key)
            first = entry is None
            if first:
                entry = self._entries[key] = _Entry(program, bucket)
            entry.count += 1
            entry.tokens += tokens
            entry.padded_tokens += padded_tokens
            entry.weight_passes += weight_passes
            pad_frac = None
            if padded_tokens:
                # census padding accounting (no fence): the live
                # engine_padding_waste_frac gauge the batch-width /
                # spec-width ladders are steered against
                if len(self._pad_window) == self._pad_window.maxlen:
                    old_u, old_p = self._pad_window[0]
                    self._pad_useful -= old_u
                    self._pad_padded -= old_p
                self._pad_window.append((tokens, padded_tokens))
                self._pad_useful += tokens
                self._pad_padded += padded_tokens
                if self._pad_padded:
                    pad_frac = 1.0 - self._pad_useful / self._pad_padded
            if timed:
                # issue seconds only for TIMED commits: attributed_s() sums
                # device+queue+issue, and mixing census issue time with
                # 1/N-sampled device time would make the total meaningless
                # in sample mode (mode=on — the bench's attribution pass —
                # times everything, so nothing is lost there)
                entry.issue_s += issue_s
            perf = self._perf
            stride = (self._sample_n
                      if self._mode == "sample" and not pre_measured else 1)
            gauge_sums = None
            if timed:
                entry.timed += 1
                entry.device_s += device_s
                entry.queue_s += queue_s
                entry.timed_tokens += tokens
                if defer_census:
                    entry.pending_timed += 1
                entry.last_timed_mono = time.monotonic()
                self._last_timed_mono = entry.last_timed_mono
                if entry.first_timed_s is None:
                    entry.first_timed_s = device_s
                entry.window.append((tokens, device_s, weight_passes))
                if weight_passes:
                    self._bw_window.append((weight_passes, device_s))
                if perf is not None:
                    # window sums gathered under the lock — deques must not
                    # be iterated while another committer appends
                    gauge_sums = (
                        sum(t for t, _, _ in entry.window),
                        sum(d for _, d, _ in entry.window),
                        sum(w for w, _ in self._bw_window),
                        sum(d for _, d in self._bw_window),
                    )
            if first:
                event = self._first_seen_locked(key)
            else:
                event = None
        # metrics + hazards OUTSIDE the lock (REGISTRY has its own locks;
        # the SLO sink may take the tracker's)
        if pad_frac is not None:
            REGISTRY.gauge("engine_padding_waste_frac").set(
                round(pad_frac, 4))
        if timed:
            # sampled mode extrapolates by the stride so the Prometheus
            # counter tracks attributed seconds, not 1/N of them
            REGISTRY.counter(
                "engine_device_seconds",
                labels={"program": program, "bucket": bucket}).inc(
                device_s * stride)
            if gauge_sums is not None:
                self._update_gauges(program, perf, mfu, gauge_sums)
        elif first:
            # the family exists from the key's first (untimed) dispatch on;
            # engine_mfu only for model-forward programs — a permanently-0
            # gauge for kv/encoder programs would average a fake idle chip
            # into any aggregation over the program label
            REGISTRY.counter("engine_device_seconds",
                             labels={"program": program, "bucket": bucket})
            if mfu:
                REGISTRY.gauge("engine_mfu", labels={"program": program})
        if event is not None:
            self._announce_compile(event)

    def note_fetch(self, steps: float = 0.0) -> None:
        """Account one device→host result fetch (the scheduler's _fetch
        helper is the ONE sanctioned call site — tpulint's devtime-fence
        rule flags any other bare ``jax.device_get`` on the hot path).
        ``steps`` is the decode steps the fetched block carries (0 for
        non-decode fetches: first-token snapshots, KV exports) — positive
        values feed the trailing window behind ``engine_steps_per_fetch``.
        Census semantics: no fence, counts in every mode."""
        if steps > 0:
            with self._lock:
                if len(self._fetch_window) == self._fetch_window.maxlen:
                    self._fetch_steps -= self._fetch_window[0]
                self._fetch_window.append(steps)
                self._fetch_steps += steps
                spf = self._fetch_steps / len(self._fetch_window)
            REGISTRY.gauge("engine_steps_per_fetch").set(round(spf, 2))
        REGISTRY.counter("engine_host_fetches_total").inc()

    def steps_per_fetch(self) -> float:
        """Mean decode steps delivered per result fetch over the trailing
        window (0.0 with no data) — the flight recorder's
        ``steps_per_fetch`` field and the roofline bench read this."""
        with self._lock:
            n = len(self._fetch_window)
            return self._fetch_steps / n if n else 0.0

    def note_tokens(self, program: str, bucket: Any, tokens: float,
                    padded_tokens: float) -> None:
        """Deferred useful-vs-padded census for a dispatch whose useful
        token count is only known at RESULT time: a multi-step decode scan
        early-exits on device (EOS / stop maybe-match pause), so tokens
        actually emitted per slot are in the fetched block, not the
        dispatch plan. The dispatch-time :meth:`commit` carries the timing
        and compile-watch with no token census; this adds the honest
        counts once the block lands, so ``engine_padding_waste_frac``
        prices early-exited scan steps as the padding they are."""
        bucket = str(bucket)
        with self._lock:
            entry = self._entries.get((program, bucket))
            if entry is None:
                entry = self._entries[(program, bucket)] = _Entry(program,
                                                                  bucket)
            entry.tokens += tokens
            entry.padded_tokens += padded_tokens
            if entry.pending_timed > 0:
                # settle a TIMED defer_census commit: its device_s landed
                # at dispatch time with zero tokens — pairing the census
                # back keeps phase_rates / MFU window sums honest
                entry.pending_timed -= 1
                entry.timed_tokens += tokens
                entry.window.append((tokens, 0.0, 0.0))
            pad_frac = None
            if padded_tokens:
                if len(self._pad_window) == self._pad_window.maxlen:
                    old_u, old_p = self._pad_window[0]
                    self._pad_useful -= old_u
                    self._pad_padded -= old_p
                self._pad_window.append((tokens, padded_tokens))
                self._pad_useful += tokens
                self._pad_padded += padded_tokens
                if self._pad_padded:
                    pad_frac = 1.0 - self._pad_useful / self._pad_padded
        if pad_frac is not None:
            REGISTRY.gauge("engine_padding_waste_frac").set(
                round(pad_frac, 4))

    def _update_gauges(self, program: str, perf, mfu: bool,
                       sums: Tuple[float, float, float, float]) -> None:
        wt, wd, bw_w, bw_d = sums
        if mfu:
            m = perf.mfu(wt, wd)
            if m is not None:
                REGISTRY.gauge("engine_mfu",
                               labels={"program": program}).set(round(m, 4))
        util = perf.hbm_read_util(bw_w, bw_d)
        if util is not None:
            REGISTRY.gauge("engine_hbm_read_util").set(round(util, 4))

    # -------------------------------------------------------- compile-watch

    def _first_seen_locked(self, key: Tuple[str, str]) -> Optional[Dict]:
        """Caller holds the lock. A key's first dispatch is a compile event
        unless warmup compiled it; one seen mid-serving is a RECOMPILE."""
        if key in self._warm:
            return None
        event = {
            "program": key[0], "bucket": key[1],
            "ts_unix": round(time.time(), 3),
            "during_serving": self._serving,
        }
        self._compiles.append(event)
        return event

    def _announce_compile(self, event: Dict[str, Any]) -> None:
        if not event["during_serving"]:
            return
        REGISTRY.counter("engine_recompiles_total").inc()
        FLIGHT.event("recompile", program=event["program"],
                     bucket=event["bucket"])
        logger.warning(
            "mid-serving XLA compile: program %s bucket %s was never warmed "
            "— live requests stall behind this compile (latency cliff); "
            "see GET /debug/compiles", event["program"], event["bucket"])
        if not self.timing_enabled:
            return   # default off-mode: observe-only, no pressure coupling
        try:
            sink = self.hazard_sink
            if sink is not None:
                sink("recompile", dict(event))
            else:
                from generativeaiexamples_tpu.observability import slo
                slo.SLO.note_hazard("recompile", dict(event))
        except Exception as exc:
            logger.debug("recompile hazard sink failed: %s", exc)

    # ------------------------------------------------------------ reporting

    def attributed_s(self) -> float:
        """Total seconds the ledger can attribute to named programs
        (device + queue + issue; timed samples only, no extrapolation)."""
        with self._lock:
            return sum(e.device_s + e.queue_s + e.issue_s
                       for e in self._entries.values())

    def last_timed_age_s(self) -> Optional[float]:
        """Seconds since the newest timed commit (None = never timed) —
        how stale the live MFU/HBM gauges are: they hold their last
        trailing-window value while the engine idles, so a consumer must
        pair the value with this age."""
        with self._lock:
            last = self._last_timed_mono
        return None if last is None else max(0.0, time.monotonic() - last)

    def fresh_programs(self, max_age_s: float = 60.0) -> set:
        """Programs with a timed commit inside the trailing window — the
        per-program gauges (``engine_mfu{program}``) HOLD their last
        value forever, so consumers aggregating across programs (the
        usage plane's worker card) must restrict to programs that are
        actually still dispatching or a one-off prefill burst's MFU
        would read as 'current' all day."""
        now = time.monotonic()
        with self._lock:
            return {e.program for e in self._entries.values()
                    if e.last_timed_mono is not None
                    and now - e.last_timed_mono <= max_age_s}

    def phase_rates(self) -> Dict[str, Optional[float]]:
        """Timed device-seconds per useful token for the two model-forward
        program families — ``prefill`` (prefill / prefill_long) and
        ``decode`` (decode / mixed variants) — the proration join the
        usage plane (observability/usage.py) bills requests with.  Rates
        come from TIMED dispatches only (device_s over timed_tokens, both
        recorded by the same sampled commits), so sample-mode stride
        never skews the ratio; a family with no timed samples (the
        default off mode) reports None and billing falls back to token
        counts."""
        sums = {"prefill": [0.0, 0.0], "decode": [0.0, 0.0]}
        with self._lock:
            for entry in self._entries.values():
                if not entry.timed or not entry.timed_tokens:
                    continue
                if entry.program.startswith("prefill"):
                    fam = sums["prefill"]
                elif entry.program.startswith(("decode", "mixed")):
                    fam = sums["decode"]
                else:
                    continue
                fam[0] += entry.device_s
                fam[1] += entry.timed_tokens
        return {k: (s / t if t else None) for k, (s, t) in sums.items()}

    def padding_waste(self) -> float:
        """Padded-token fraction NOT carrying useful positions over the
        trailing commit window (0.0 with no data) — the flight recorder's
        ``padding_waste_frac`` field and the batch-width ladder's
        scoreboard read this."""
        with self._lock:
            pad_u, pad_p = self._pad_useful, self._pad_padded
        return (1.0 - pad_u / pad_p) if pad_p else 0.0

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /debug/devtime`` body."""
        with self._lock:
            entries = sorted(self._entries.values(),
                             key=lambda e: -(e.device_s or e.count))
            rows = [e.snapshot() for e in entries]
            perf = self._perf
            mode, sample_n = self._mode, self._sample_n
            serving = self._serving
            pad_u, pad_p = self._pad_useful, self._pad_padded
        totals = {
            "count": sum(r["count"] for r in rows),
            "timed": sum(r["timed"] for r in rows),
            "device_s": round(sum(r["device_s"] for r in rows), 6),
            "queue_s": round(sum(r["queue_s"] for r in rows), 6),
            "issue_s": round(sum(r["issue_s"] for r in rows), 6),
        }
        out: Dict[str, Any] = {
            "mode": mode, "sample_n": sample_n, "serving": serving,
            "programs": rows, "totals": totals,
            "padding_waste_frac": (round(1.0 - pad_u / pad_p, 4)
                                   if pad_p else 0.0),
            "steps_per_fetch": round(self.steps_per_fetch(), 2),
            "host_fetches_total": REGISTRY.counter(
                "engine_host_fetches_total").value,
            "recompiles_total": REGISTRY.counter(
                "engine_recompiles_total").value,
        }
        if perf is not None:
            out["perf_model"] = {
                "n_params": perf.n_params,
                "param_bytes": perf.param_bytes,
                "peak_flops": perf.peak_flops,
                "peak_bw": perf.peak_bw,
            }
        return out

    def compiles(self) -> Dict[str, Any]:
        """The ``GET /debug/compiles`` body: every compile event (newest
        first) with its trigger key; ``during_serving`` marks the
        recompiles (the latency cliffs)."""
        with self._lock:
            events = list(self._compiles)[::-1]
            warm = len(self._warm)
        return {
            "events": events,
            "warmed_keys": warm,
            "recompiles_total": REGISTRY.counter(
                "engine_recompiles_total").value,
        }


DEVTIME = DevtimeLedger()
