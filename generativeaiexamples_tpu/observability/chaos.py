"""Deterministic fault-injection plane: prove the failure semantics, don't
hope for them.

The disaggregated dataplane (router → prefill worker → KV handoff → decode
replica, PR 6) grew real failure paths — circuit-breaking, mid-stream
resume, topology-collapse fallback, handoff validation — but each was only
exercised by the bespoke test that shipped it. This module makes failure a
first-class, *reproducible* input: a seeded schedule of injected faults at
the seams that actually break in production, so the chaos test matrix
(tests/test_chaos.py), the fuzz harness, and ``bench.py --chaos`` can all
drive the same fault classes and assert the same contract — every stream
either completes token-identical after recovery or terminates with a loud
typed error; never a hang, never silent corruption.

Gating follows the ``APP_DEVTIME`` pattern exactly: ``APP_CHAOS`` is
``off`` by default and off means ZERO work on hot paths — call sites guard
on :attr:`ChaosPlane.enabled` (one attribute read) and a tier-1 test
enforces that no fault decision (no RNG draw, no sleep, no counter) ever
happens in off mode (tests/test_chaos.py, the analogue of devtime's
zero-fence test).

Fault catalog (``APP_CHAOS_SPEC``, comma-separated
``fault=prob[/param[/max]]`` entries — ``param`` is fault-specific,
``max`` caps total injections for deterministic "fail N times then
recover" schedules):

  * ``http.delay``  — sleep ``param`` seconds before a dispatch
                      (client side) or before serving (engine side);
  * ``http.drop``   — connection reset on a router→worker dispatch
                      (raises :class:`ChaosConnectionReset`, a
                      ``ConnectionResetError`` — the router's transport-
                      failure path handles it like a real peer death);
  * ``http.error``  — a 5xx: client side raises :class:`ChaosHttpError`
                      (a ``ConnectionError``), engine side answers 503;
  * ``kv.truncate`` — drop the last page row of an exported KV handoff
                      payload (the decode side MUST 409 loudly —
                      ``validate_handoff`` cross-checks buffer shapes);
                      on the BINARY wire (:meth:`ChaosPlane.corrupt_wire`)
                      also truncates the encoded frame's tail — the frame
                      length prefix must 400 it;
  * ``kv.garble``   — corrupt the payload's geometry metadata
                      (page_size), same loud-409 contract; on the binary
                      wire, flips bits inside a raw array segment — only
                      the per-segment crc32 can catch that (loud 400);
  * ``tick.stall``  — sleep ``param`` seconds inside a scheduler tick
                      (what the engine watchdog exists to detect);
  * ``page.exhaust``— force a KV page allocation to fail (pool-pressure
                      preemption storms on demand);
  * ``worker.die``  — raise :class:`ChaosWorkerDeath` inside a scheduler
                      tick: the driver's crash path fails every in-flight
                      request loudly and resets (engine/scheduler._loop).

Determinism: every fault key draws from its own ``random.Random`` stream
seeded by ``(APP_CHAOS_SEED, fault)``, so the decision sequence for one
fault class is a pure function of the seed and that class's call count —
independent of how other fault sites interleave. The same seed + spec +
workload replays the same fault schedule.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Dict, Optional, Tuple

from generativeaiexamples_tpu.core.metrics import REGISTRY

logger = logging.getLogger(__name__)

_MODES = ("off", "on")

# fault -> default param (seconds for delays/stalls; unused otherwise)
_FAULTS: Dict[str, float] = {
    "http.delay": 0.05,
    "http.drop": 0.0,
    "http.error": 0.0,
    "kv.truncate": 0.0,
    "kv.garble": 0.0,
    "tick.stall": 0.05,
    "page.exhaust": 0.0,
    "spill.exhaust": 0.0,
    "worker.die": 0.0,
}

# a deliberately mixed default schedule for `APP_CHAOS=on` with no spec:
# transport flakiness + scheduler stalls + pool pressure, no worker death
DEFAULT_SPEC = ("http.delay=0.05/0.05,http.drop=0.03,http.error=0.03,"
                "tick.stall=0.01/0.05,page.exhaust=0.05,kv.truncate=0.02")


class ChaosFault(Exception):
    """Base of every injected-fault exception — the TYPED part of the
    'loud typed error' contract: a consumer (or test) can always tell an
    injected fault from an organic bug."""


class ChaosConnectionReset(ChaosFault, ConnectionResetError):
    """Injected connection reset on an HTTP dispatch (client side)."""


class ChaosHttpError(ChaosFault, ConnectionError):
    """Injected 5xx-equivalent transport failure (client side)."""


class ChaosWorkerDeath(ChaosFault):
    """Injected engine-driver death: the scheduler loop's crash handler
    fails every in-flight request loudly and resets device state."""


def _env_config() -> Tuple[str, int, str]:
    raw = (os.environ.get("APP_CHAOS", "").strip().lower() or "off")
    if raw not in _MODES:
        logger.warning("APP_CHAOS=%r is not off|on; using off", raw)
        raw = "off"
    try:
        seed = int(os.environ.get("APP_CHAOS_SEED", "") or 0)
    except ValueError:
        seed = 0
    spec = os.environ.get("APP_CHAOS_SPEC", "").strip()
    return raw, seed, spec


def parse_spec(spec: str) -> Dict[str, Tuple[float, float, Optional[int]]]:
    """``fault=prob[/param[/max]]`` entries → {fault: (prob, param, max)}.
    Unknown fault names are a loud ValueError — a typo'd spec silently
    injecting nothing would let a chaos run pass vacuously."""
    out: Dict[str, Tuple[float, float, Optional[int]]] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(f"chaos spec entry {entry!r} must be "
                             f"fault=prob[/param[/max]]")
        fault, rest = entry.split("=", 1)
        fault = fault.strip()
        if fault not in _FAULTS:
            raise ValueError(f"unknown chaos fault {fault!r}; known: "
                             f"{sorted(_FAULTS)}")
        parts = rest.split("/")
        prob = float(parts[0]) if parts[0] else 1.0
        param = (float(parts[1]) if len(parts) > 1 and parts[1]
                 else _FAULTS[fault])
        cap = (int(parts[2]) if len(parts) > 2 and parts[2] else None)
        out[fault] = (max(0.0, min(1.0, prob)), param, cap)
    return out


class ChaosPlane:
    """Process-global fault injector (``CHAOS``), off by default.

    Hot call sites guard on :attr:`enabled` — when off, no method here is
    even entered (and the tier-1 zero-overhead test enforces that no
    decision is drawn either way). When on, each fault key decides from
    its own seeded RNG stream; every injection counts into
    ``chaos_injections_total{fault,site}``.
    """

    def __init__(self, mode: Optional[str] = None, seed: Optional[int] = None,
                 spec: Optional[str] = None) -> None:
        env_mode, env_seed, env_spec = _env_config()
        self._lock = threading.Lock()
        self._on = (mode if mode in _MODES else env_mode) == "on"
        self._seed = env_seed if seed is None else int(seed)
        self._spec_str = env_spec if spec is None else spec
        try:
            self._faults = parse_spec(self._spec_str or
                                      (DEFAULT_SPEC if self._on else ""))
        except ValueError as exc:
            # env-sourced construction happens at IMPORT in every process
            # (engine, router, chains): a stale/typo'd APP_CHAOS_SPEC must
            # not take the stack down — least so with chaos off. Warn and
            # DISABLE rather than fall back to a default schedule: a typo'd
            # spec silently injecting something else would make a chaos
            # run's numbers lie. configure() (deliberate, runtime) still
            # raises loudly.
            logger.warning("ignoring invalid APP_CHAOS_SPEC (%s); "
                           "chaos DISABLED", exc)
            self._on = False
            self._faults = {}
        self._rngs: Dict[str, random.Random] = {}
        self._counts: Dict[str, int] = {}      # injections per fault
        self._draws: Dict[str, int] = {}       # decisions per fault
        # injectable sleep so tests and the fuzz harness can run stall
        # schedules without real wall-clock cost
        self.sleep = time.sleep

    # ------------------------------------------------------------ lifecycle

    @property
    def enabled(self) -> bool:
        return self._on

    def configure(self, mode: Optional[str] = None,
                  seed: Optional[int] = None,
                  spec: Optional[str] = None) -> None:
        """Runtime override (tests, bench's chaos round). Resets the RNG
        streams and counters so a configured run replays from decision 0."""
        with self._lock:
            if mode is not None:
                if mode not in _MODES:
                    raise ValueError(f"chaos mode must be one of {_MODES}, "
                                     f"got {mode!r}")
                self._on = mode == "on"
            if seed is not None:
                self._seed = int(seed)
            if spec is not None:
                self._spec_str = spec
            self._faults = parse_spec(
                self._spec_str or (DEFAULT_SPEC if self._on else ""))
            self._rngs.clear()
            self._counts.clear()
            self._draws.clear()

    def reset(self) -> None:
        """Back to the environment's configuration (tests) — including
        the injectable sleep, so a test that swapped it cannot leak a
        no-op sleep into later chaos runs in the same process."""
        mode, seed, spec = _env_config()
        self.configure(mode=mode, seed=seed, spec=spec)
        self.sleep = time.sleep

    # ------------------------------------------------------------- deciding

    def _decide(self, fault: str) -> Optional[float]:
        """One deterministic decision for ``fault``: the param when this
        call injects, None otherwise. THE enforcement point of the
        zero-overhead contract — the tier-1 off-mode test monkeypatches
        this and asserts it is never reached."""
        with self._lock:
            entry = self._faults.get(fault)
            if entry is None:
                return None
            prob, param, cap = entry
            if cap is not None and self._counts.get(fault, 0) >= cap:
                return None
            rng = self._rngs.get(fault)
            if rng is None:
                rng = self._rngs[fault] = random.Random(
                    f"{self._seed}:{fault}")
            self._draws[fault] = self._draws.get(fault, 0) + 1
            if rng.random() >= prob:
                return None
            self._counts[fault] = self._counts.get(fault, 0) + 1
        return param

    def _record(self, fault: str, site: str) -> None:
        REGISTRY.counter("chaos_injections_total",
                         labels={"fault": fault, "site": site}).inc()
        logger.info("chaos: injected %s at %s", fault, site)

    # ---------------------------------------------------------------- hooks

    def http_fault(self, site: str) -> None:
        """Client-side HTTP fault at a dispatch site (server/failover.py):
        may sleep (http.delay), raise :class:`ChaosConnectionReset`
        (http.drop), or raise :class:`ChaosHttpError` (http.error). Call
        INSIDE the dispatch's try block so the injected failure takes the
        same retry/circuit-break path a real one would."""
        if not self._on:
            return
        delay = self._decide("http.delay")
        if delay is not None:
            self._record("http.delay", site)
            self.sleep(delay)
        if self._decide("http.drop") is not None:
            self._record("http.drop", site)
            raise ChaosConnectionReset(f"chaos: connection reset at {site}")
        if self._decide("http.error") is not None:
            self._record("http.error", site)
            raise ChaosHttpError(f"chaos: injected 5xx at {site}")

    def server_fault(self, site: str) -> Optional[Tuple[str, float]]:
        """Server-side HTTP fault decision for an async handler (engine/
        server.py): ``("delay", seconds)`` — the handler must await-sleep
        it, never block the loop — or ``("error", 0)`` — answer 503 — or
        None. Drop stays a client-side fault (a server cannot portably
        fake a TCP reset from inside aiohttp)."""
        if not self._on:
            return None
        delay = self._decide("http.delay")
        if delay is not None:
            self._record("http.delay", site)
            return ("delay", delay)
        if self._decide("http.error") is not None:
            self._record("http.error", site)
            return ("error", 0.0)
        return None

    def corrupt_kv(self, payload: Dict[str, Any],
                   site: str = "kv") -> Dict[str, Any]:
        """Maybe corrupt an exported KV handoff payload (prefill side,
        BEFORE wire encoding). Truncation drops the last page row of every
        buffer; garbling bumps the claimed page_size. Either way the
        decode side's ``validate_handoff`` must refuse with a loud 409 —
        the contract this fault class exists to prove (served garbage KV
        would be silent corruption, the one unforgivable outcome)."""
        if not self._on:
            return payload
        if self._decide("kv.truncate") is not None:
            self._record("kv.truncate", site)
            out = dict(payload)
            for key in ("k", "v", "k_s", "v_s"):
                arr = out.get(key)
                if arr is not None and getattr(arr, "ndim", 0) >= 2 \
                        and arr.shape[1] > 0:
                    out[key] = arr[:, :-1]
            return out
        if self._decide("kv.garble") is not None:
            self._record("kv.garble", site)
            out = dict(payload)
            out["page_size"] = int(out.get("page_size", 0) or 0) + 1
            return out
        return payload

    def corrupt_wire(self, body: bytes, site: str = "kv.wire") -> bytes:
        """Maybe corrupt an ENCODED binary KV frame (prefill side, AFTER
        wire encoding — the transport-level counterpart of
        :meth:`corrupt_kv`). Truncation drops the body's tail; garbling
        flips bits inside the segment area. Either way the decode side's
        frame validation (core/kv_wire.decode_kv_frames: length prefix +
        per-segment crc32) must refuse with a loud 400 BEFORE
        ``validate_handoff`` — raw binary segments stay shape-valid under
        bit flips, so without the crc this fault class would be served as
        silent garbage KV (the JSON wire gets its equivalent check free
        from the b64/JSON parse)."""
        if not self._on:
            return body
        if self._decide("kv.truncate") is not None:
            self._record("kv.truncate", site)
            return body[:max(8, len(body) - max(1, len(body) // 4))]
        if self._decide("kv.garble") is not None:
            self._record("kv.garble", site)
            # flip bytes at 3/4 depth: for any real payload that lands in
            # an array segment (headers are a few hundred bytes of a
            # multi-KB body), which only the crc32 can catch
            out = bytearray(body)
            pos = (len(out) * 3) // 4
            for i in range(pos, min(pos + 8, len(out))):
                out[i] ^= 0xFF
            return bytes(out)
        return body

    def tick_fault(self, site: str = "scheduler") -> None:
        """Scheduler-tick fault (engine/scheduler._tick): a stall (sleep —
        the watchdog's tick-heartbeat detects sustained ones) or worker
        death (raise — the driver loop's crash handler fails all in-flight
        requests loudly and resets)."""
        if not self._on:
            return
        stall = self._decide("tick.stall")
        if stall is not None:
            self._record("tick.stall", site)
            self.sleep(stall)
        if self._decide("worker.die") is not None:
            self._record("worker.die", site)
            raise ChaosWorkerDeath(f"chaos: worker death injected at {site}")

    def spill_fault(self, site: str = "kv_spill") -> bool:
        """Force the host spill pool to refuse a demotion (pool-exhaust on
        demand): the scheduler treats True exactly like an over-budget
        pool — the preemption falls back to the recompute path, which must
        stay token-identical (the fuzz spill menus assert it)."""
        if not self._on:
            return False
        if self._decide("spill.exhaust") is not None:
            self._record("spill.exhaust", site)
            return True
        return False

    def page_fault(self, site: str = "kv_pages") -> bool:
        """Force a KV page allocation to fail (pool exhaustion on demand):
        the scheduler treats True exactly like an empty allocator — head-
        of-line waits, page growth preempts the youngest slot."""
        if not self._on:
            return False
        if self._decide("page.exhaust") is not None:
            self._record("page.exhaust", site)
            return True
        return False

    # ------------------------------------------------------------ reporting

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /debug/chaos`` body: mode, seed, active spec, and
        per-fault decision/injection counts."""
        with self._lock:
            faults = {
                fault: {"prob": prob, "param": param, "max": cap,
                        "decisions": self._draws.get(fault, 0),
                        "injected": self._counts.get(fault, 0)}
                for fault, (prob, param, cap) in sorted(self._faults.items())
            }
        return {"mode": "on" if self._on else "off",
                "seed": self._seed,
                "spec": self._spec_str or (DEFAULT_SPEC if self._on else ""),
                "faults": faults}


CHAOS = ChaosPlane()
