"""Canonical fleet event trace — the replayable record of every scheduling
decision (docs/simulation.md).

The flight recorder (observability/flight.py) answers "what did the gauges
look like"; this plane answers "what exactly happened, in what order":
one schema-versioned record per admission, QoS decision, dispatch,
preemption, spill, tier promote, migration, router placement, and finish —
stamped with the mono clock (core/clock.py, so simulated runs stamp
virtual time), tenant, token counts, prefix hash, and the perfmodel
estimated cost where one exists. A trace is sufficient for
``ops/simulate.py`` to reconstruct the arrival process and re-drive the
REAL policy objects, which is the whole point: record once, replay any
what-if.

Gating follows the house zero-overhead pattern (``APP_TRACE=off|on``,
default off): call sites in hot paths guard on ``TRACE.enabled`` — one
attribute read, no record built, no lock touched. Enabled, records land
in a bounded ring (``APP_TRACE_CAPACITY``, default 65536) served by
``GET /debug/trace?window=`` and ``flight.dump()``; with
``APP_TRACE_PATH`` set they are ALSO write-behind appended as JSONL and
size-rotated (``APP_TRACE_ROTATE_MB``, one ``.1`` predecessor kept) so a
long serving run's trace survives the ring.

Record shape (schema v1)::

    {"v": 1, "seq": 17, "mono": 12.034, "kind": "dispatch", ...fields}

``seq`` is a process-wide total order (the mono stamp alone cannot break
ties inside one tick). Field vocabulary per kind is documented in
docs/simulation.md and deliberately flat — every value JSON-scalar — so
a trace line greps and a replayer never needs nested parsing.
"""

from __future__ import annotations

import atexit
import json
import os
import queue as queue_mod
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.observability.lockwatch import tracked_lock

SCHEMA_VERSION = 1

_DEF_CAPACITY = 65536
_DEF_ROTATE_MB = 64
_FLUSH_EVERY = 128


def _env_mode() -> str:
    return (os.environ.get("APP_TRACE", "").strip().lower() or "off")


class EventTrace:
    """Bounded, optionally disk-rotated event trace (process-global
    ``TRACE``). Thread-safe: the scheduler driver thread, router worker
    threads, and HTTP handlers all emit into the same stream."""

    def __init__(self) -> None:
        self.enabled = _env_mode() in ("on", "1", "true")
        cap = int(os.environ.get("APP_TRACE_CAPACITY", "") or _DEF_CAPACITY)
        self.capacity = max(256, cap)
        self.path = os.environ.get("APP_TRACE_PATH", "").strip() or None
        self.rotate_bytes = int(float(
            os.environ.get("APP_TRACE_ROTATE_MB", "") or _DEF_ROTATE_MB)
            * 1024 * 1024)
        self._ring: "deque[dict]" = deque(maxlen=self.capacity)
        self._pending: List[str] = []
        self._seq = 0
        self._total = 0
        self._lock = tracked_lock("trace._lock")
        # write-behind: full batches drain on ONE dedicated writer thread
        # (started lazily at first batch), so file I/O never runs on an
        # emitting thread — the driver tick and HTTP handlers pay one
        # list-append, never an fsync
        self._wq: "queue_mod.Queue[Optional[List[str]]]" = queue_mod.Queue()
        self._inflight = 0            # batches enqueued, not yet on disk
        self._writer: Optional[threading.Thread] = None
        # a bench worker subprocess may exit with < _FLUSH_EVERY lines
        # buffered; close() flushes them and bounded-joins the writer so
        # the daemon never dies mid-write at interpreter exit
        atexit.register(self.close)

    # -- configuration (bench / simulator / tests) -----------------------

    def configure(self, mode: Optional[str] = None,
                  path: Optional[str] = None,
                  capacity: Optional[int] = None) -> None:
        """Runtime re-arm: bench rounds and the simulator switch tracing
        on without re-execing the process. ``path=''`` detaches the file
        sink; a new capacity re-rings (drops history)."""
        with self._lock:
            if mode is not None:
                self.enabled = mode.strip().lower() in ("on", "1", "true")
            if path is not None:
                self.path = path or None
            if capacity is not None:
                self.capacity = max(256, int(capacity))
                self._ring = deque(self._ring, maxlen=self.capacity)

    def reset(self) -> None:
        """Drop all recorded state (simulator runs start from a clean
        stream; live servers never call this)."""
        with self._lock:
            self._ring.clear()
            self._pending = []
            self._seq = 0
            self._total = 0

    # -- recording -------------------------------------------------------

    def emit(self, kind: str, **fields) -> None:
        """Append one event. Callers on hot paths guard with
        ``if TRACE.enabled`` so the disabled cost is one attribute read;
        this re-check only closes the configure() race."""
        if not self.enabled:
            return
        rec = {"v": SCHEMA_VERSION, "mono": clock.mono(), "kind": kind}
        rec.update(fields)
        batch: Optional[List[str]] = None
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self._total += 1
            self._ring.append(rec)
            if self.path is not None:
                self._pending.append(json.dumps(rec, separators=(",", ":"),
                                                default=str))
                if len(self._pending) >= _FLUSH_EVERY:
                    batch, self._pending = self._pending, []
                    self._inflight += 1
        if batch is not None:
            self._wq.put(batch)
            self._ensure_writer()

    def flush(self, timeout_s: float = 2.0) -> None:
        """Push buffered lines to the file sink and bounded-wait for the
        writer to land every in-flight batch: dump paths and tests read
        the file synchronously after this returns."""
        batch: Optional[List[str]] = None
        with self._lock:
            if self._pending:
                batch, self._pending = self._pending, []
                self._inflight += 1
            waiting = self._inflight > 0
        if batch is not None:
            self._wq.put(batch)
        if not waiting:
            return
        self._ensure_writer()
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    return
            time.sleep(0.002)

    def close(self, timeout_s: float = 2.0) -> None:
        """Bounded shutdown (atexit): flush buffered lines, then
        sentinel-stop the writer thread and join with a deadline so a
        slow disk can never hang interpreter exit."""
        self.flush(timeout_s)
        with self._lock:
            t, self._writer = self._writer, None
        if t is not None and t.is_alive():
            self._wq.put(None)
            t.join(timeout_s)

    def _ensure_writer(self) -> None:
        with self._lock:
            if self._writer is not None and self._writer.is_alive():
                return
            self._writer = threading.Thread(target=self._writer_loop,
                                            name="trace-writer", daemon=True)
            self._writer.start()

    def _writer_loop(self) -> None:
        while True:
            batch = self._wq.get()
            if batch is None:
                return
            try:
                self._write(batch)
            finally:
                with self._lock:
                    self._inflight -= 1

    def _write(self, lines: List[str]) -> None:
        # file I/O happens on the writer thread with NO lock held
        # (lock-discipline): emitters keep appending to the ring/buffer
        # while this thread writes
        path = self.path
        if not path:
            return
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write("\n".join(lines) + "\n")
            if os.path.getsize(path) > self.rotate_bytes:
                os.replace(path, path + ".1")
        except OSError:
            # a full disk must never take the serving thread down; the
            # ring keeps the recent window either way
            from generativeaiexamples_tpu.core.metrics import REGISTRY
            REGISTRY.counter("trace_write_errors_total").inc()

    # -- read surface ----------------------------------------------------

    def describe(self) -> Dict[str, object]:
        with self._lock:
            buffered = len(self._ring)
            total = self._total
        return {
            "schema_version": SCHEMA_VERSION,
            "enabled": self.enabled,
            "mode": "on" if self.enabled else "off",
            "capacity": self.capacity,
            "buffered": buffered,
            "recorded_total": total,
            "dropped": max(0, total - buffered),
            "path": self.path,
        }

    def window(self, seconds: float, limit: int = 4096,
               kinds: Optional[Iterable[str]] = None) -> List[dict]:
        """Events from the last ``seconds`` of mono time, newest ``limit``
        kept, oldest-first — the /debug/trace body and the flight dump's
        trace tail both read through here."""
        cutoff = clock.mono() - max(0.0, float(seconds))
        want = frozenset(kinds) if kinds is not None else None
        with self._lock:
            recs = [r for r in self._ring
                    if r.get("mono", 0.0) >= cutoff
                    and (want is None or r.get("kind") in want)]
        if limit and len(recs) > limit:
            recs = recs[-limit:]
        return recs

    def records(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def dump_jsonl(self, path: str) -> int:
        """Write the full ring as JSONL (the simulator's input format —
        identical line shape to the rotation sink). Returns the record
        count."""
        recs = self.records()
        with open(path, "w", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec, separators=(",", ":"),
                                   default=str) + "\n")
        return len(recs)


def read_jsonl(path: str) -> List[dict]:
    """Load a trace file (ring dump or rotated sink) — tolerant of a
    torn final line from a killed process, loud on anything else."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                # a mid-write kill can tear the last line; anything torn
                # earlier means the file is not a trace
                remainder = f.read().strip()
                if remainder:
                    raise ValueError(
                        f"{path}:{i + 1}: undecodable trace line")
                break
            out.append(rec)
    return out


TRACE = EventTrace()
