"""Minimal OpenTelemetry-compatible tracing core.

The reference wires `opentelemetry-sdk` with an OTLP gRPC exporter, a
SimpleSpanProcessor, and a W3C TraceContext propagator
(ref: RAG/src/chain_server/tracing.py:36-59), then converts LangChain /
LlamaIndex lifecycle events into spans via callback handlers
(ref: RAG/tools/observability/langchain/opentelemetry_callback.py:137-606).

This module provides the same span model in-tree with zero hard deps:

  * ``Tracer.span(name)`` context manager → ``Span`` with trace_id/span_id,
    parent linkage, attributes, events, status, wall-time;
  * W3C ``traceparent`` header inject/extract for cross-service propagation
    (ref: tracing.py:46, chat_client.py:43 carrier propagation);
  * exporters: console, in-memory (tests), JSONL file (offline analysis —
    the stand-in for the OTLP→Jaeger pipeline in
    RAG/tools/observability/configs/otel-collector-config.yaml);
  * tail-filtering of health-check spans, matching the collector's
    tail_sampling drop of ``/health`` (otel-collector-config.yaml:10-20).

Tracing is opt-in via ``ENABLE_TRACING=true`` (ref: tracing.py:38,44); when
disabled every API is a cheap no-op.
"""

from __future__ import annotations

import contextvars
import json
import os
import secrets
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Mapping, Optional

_current_span: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "gaie_tpu_current_span", default=None
)

# The serving-layer request id (X-Request-Id) for the request being handled
# on THIS thread/task: stage_span stamps it on every pipeline-stage span so
# timelines (/debug/requests/<id>), spans, and SLO breach records join on
# one key. Set by the chain server inside its StreamDrain reader thread
# (contextvars do not cross threads, so it is established where the chain
# actually executes).
_request_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "gaie_tpu_request_id", default=""
)


def set_request_id(request_id: str) -> "contextvars.Token[str]":
    return _request_id.set(request_id)


def reset_request_id(token: "contextvars.Token[str]") -> None:
    _request_id.reset(token)


def current_request_id() -> str:
    return _request_id.get()


def tracing_enabled() -> bool:
    return os.environ.get("ENABLE_TRACING", "").strip().lower() in ("1", "true", "yes")


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    status: str = "OK"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, attributes: Optional[Mapping[str, Any]] = None) -> None:
        """Record a point-in-time event (used per-token in the stream hot loop,
        mirroring on_llm_new_token spans, ref opentelemetry_callback.py:230)."""
        self.events.append({
            "name": name,
            "time_ns": time.time_ns(),
            "attributes": dict(attributes or {}),
        })

    def record_exception(self, exc: BaseException) -> None:
        self.status = "ERROR"
        self.add_event("exception", {"type": type(exc).__name__, "message": str(exc)})

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "duration_ms": (self.end_ns - self.start_ns) / 1e6,
            "attributes": self.attributes,
            "events": self.events,
            "status": self.status,
        }


class SpanExporter:
    def export(self, span: Span) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class ConsoleSpanExporter(SpanExporter):
    def export(self, span: Span) -> None:
        print(json.dumps(span.to_dict(), default=str))


class InMemorySpanExporter(SpanExporter):
    """Test exporter (the stand-in for Jaeger assertions)."""

    def __init__(self) -> None:
        self.spans: List[Span] = []
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()


class JsonlSpanExporter(SpanExporter):
    """Append spans as JSON lines — offline replacement for OTLP→Jaeger."""

    def __init__(self, path: str) -> None:
        self._path = path
        self._lock = threading.Lock()

    def export(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), default=str)
        with self._lock:
            with open(self._path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")


class OTLPHTTPSpanExporter(SpanExporter):
    """OTLP/HTTP JSON exporter: spans land in any OTLP collector (Jaeger
    all-in-one, otel-collector, Tempo) at ``<endpoint>/v1/traces``.

    In-tree replacement for the reference's OTLP-gRPC → collector pipeline
    (ref: RAG/src/chain_server/tracing.py:36-59 exporter setup;
    RAG/tools/observability/configs/otel-collector-config.yaml) with the
    collector's PROCESSING folded in, since there is no collector sidecar
    to do it here:

      * health-probe spans never reach the wire (the Tracer's tail filter,
        = the collector's tail_sampling drop, config lines 10-20);
      * collection/document ids in ``http.target`` / ``http.url`` are
        anonymized to ``{collection_id}``/``{document_id}`` placeholders
        (= the collector's transform replace_patterns, lines 21-43).

    Spans batch on a background thread (flush every ``batch_size`` spans or
    ``flush_interval_s``); export() never blocks the traced request path.
    A dead collector drops batches with one warning, not one per span.
    """

    _ANON = [
        (r"/collections/[\w-]+/documents/[\w-]+",
         "/collections/{collection_id}/documents/{document_id}"),
        (r"/collections/[\w-]+/search", "/collections/{collection_id}/search"),
        (r"/collections/[\w-]+$", "/collections/{collection_id}"),
    ]

    def __init__(self, endpoint: str = "http://localhost:4318",
                 service_name: str = "generativeaiexamples-tpu",
                 batch_size: int = 32, flush_interval_s: float = 2.0,
                 anonymize: bool = True) -> None:
        import queue as _queue
        self._url = endpoint.rstrip("/") + "/v1/traces"
        self._service = service_name
        self._anonymize = anonymize
        self._batch_size = batch_size
        self._interval = flush_interval_s
        self._q: "_queue.Queue" = _queue.Queue()
        self._warned = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="otlp-export")
        self._thread.start()

    def export(self, span: Span) -> None:
        self._q.put(span)

    def shutdown(self) -> None:
        """Deterministic drain: every span export()ed before this call is
        flushed before the thread exits. The sentinel wakes a blocked
        ``_q.get`` immediately (no up-to-``flush_interval_s`` timeout wait),
        and the loop's stop path drains the queue completely before its
        final post — the old exit condition could observe ``_stop`` with a
        non-empty final batch mid-race and leave it unsent."""
        self._stop.set()
        self._q.put(None)    # wake the getter now
        self._thread.join(timeout=2 * self._interval + 10)

    # -- wire encoding -----------------------------------------------------

    @staticmethod
    def _value(v: Any) -> Dict[str, Any]:
        if isinstance(v, bool):
            return {"boolValue": v}
        if isinstance(v, int):
            return {"intValue": str(v)}
        if isinstance(v, float):
            return {"doubleValue": v}
        return {"stringValue": str(v)}

    @classmethod
    def _attrs(cls, mapping: Mapping[str, Any]) -> List[Dict[str, Any]]:
        return [{"key": k, "value": cls._value(v)} for k, v in mapping.items()]

    def _scrub(self, attrs: Dict[str, Any]) -> Dict[str, Any]:
        if not self._anonymize:
            return attrs
        import re
        out = dict(attrs)
        for key in ("http.target", "http.url", "http.path"):
            val = out.get(key)
            if isinstance(val, str):
                for pat, repl in self._ANON:
                    val = re.sub(pat, repl, val)
                out[key] = val
        return out

    def _encode(self, spans: List[Span]) -> bytes:
        wire = []
        for s in spans:
            enc = {
                "traceId": s.trace_id,
                "spanId": s.span_id,
                "name": s.name,
                "kind": 1,
                "startTimeUnixNano": str(s.start_ns),
                "endTimeUnixNano": str(s.end_ns),
                "attributes": self._attrs(self._scrub(s.attributes)),
                "events": [{"timeUnixNano": str(e["time_ns"]),
                            "name": e["name"],
                            "attributes": self._attrs(e["attributes"])}
                           for e in s.events],
                "status": {"code": 2 if s.status == "ERROR" else 1},
            }
            if s.parent_id:
                enc["parentSpanId"] = s.parent_id
            wire.append(enc)
        return json.dumps({"resourceSpans": [{
            "resource": {"attributes": self._attrs(
                {"service.name": self._service})},
            "scopeSpans": [{"scope": {"name": "generativeaiexamples_tpu"},
                            "spans": wire}],
        }]}).encode()

    # -- background flush --------------------------------------------------

    def _loop(self) -> None:
        import queue as _queue
        batch: List[Span] = []
        deadline = time.monotonic() + self._interval
        while True:
            timeout = max(0.05, deadline - time.monotonic())
            try:
                item = self._q.get(timeout=timeout)
                if item is not None:     # None = shutdown wake sentinel
                    batch.append(item)
            except _queue.Empty:
                pass
            if self._stop.is_set():
                # deterministic final drain: collect EVERYTHING already
                # queued, post it, exit — never returns with spans that
                # were export()ed before shutdown() still unsent. Posted in
                # _batch_size chunks: a busy process can shut down with a
                # full flush interval of backlog, and one giant request
                # would trip collector request-size limits and drop it all
                while True:
                    try:
                        item = self._q.get_nowait()
                    except _queue.Empty:
                        break
                    if item is not None:
                        batch.append(item)
                for i in range(0, len(batch), self._batch_size):
                    if not self._post(batch[i:i + self._batch_size]):
                        # a dead endpoint fails every later chunk too —
                        # stop rather than serialize a 5 s timeout per
                        # chunk past shutdown()'s join budget
                        break
                return
            if (len(batch) >= self._batch_size
                    or time.monotonic() >= deadline) and batch:
                self._post(batch)
                batch = []
            if time.monotonic() >= deadline:
                deadline = time.monotonic() + self._interval

    def _post(self, batch: List[Span]) -> bool:
        import urllib.error
        import urllib.request
        req = urllib.request.Request(
            self._url, data=self._encode(batch),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5):
                pass
            self._warned = False
            return True
        except (urllib.error.URLError, OSError) as exc:
            if not self._warned:   # one warning per outage, not per batch
                import logging
                logging.getLogger(__name__).warning(
                    "OTLP export to %s failed (%s); dropping spans until "
                    "the collector returns", self._url, exc)
                self._warned = True
            return False


_exporter: SpanExporter = ConsoleSpanExporter()
_drop_name_substrings = ("/health",)  # ref: otel-collector-config.yaml tail_sampling lines 10-20


def configure_from_env() -> Optional[SpanExporter]:
    """Pick the exporter from env, mirroring the reference's compose wiring
    (ref: docker-compose.yaml OTEL_EXPORTER_OTLP_ENDPOINT):

      APP_TRACING_EXPORTER = console | jsonl | otlp | memory
      APP_TRACING_OTLP_ENDPOINT (default http://localhost:4318)
      APP_TRACING_JSONL_PATH (default traces.jsonl)
    """
    kind = os.environ.get("APP_TRACING_EXPORTER", "").strip().lower()
    if not kind:
        return None
    if kind == "otlp":
        exp: SpanExporter = OTLPHTTPSpanExporter(
            endpoint=os.environ.get("APP_TRACING_OTLP_ENDPOINT",
                                    "http://localhost:4318"),
            service_name=os.environ.get("APP_TRACING_SERVICE",
                                        "generativeaiexamples-tpu"))
    elif kind == "jsonl":
        exp = JsonlSpanExporter(os.environ.get("APP_TRACING_JSONL_PATH",
                                               "traces.jsonl"))
    elif kind == "memory":
        exp = InMemorySpanExporter()
    else:
        exp = ConsoleSpanExporter()
    set_exporter(exp)
    return exp


def set_exporter(exporter: SpanExporter) -> None:
    global _exporter
    _exporter = exporter


class Tracer:
    """Factory of spans; one per instrumented component."""

    def __init__(self, name: str, enabled: Optional[bool] = None) -> None:
        self.name = name
        self._enabled = enabled

    @property
    def enabled(self) -> bool:
        return tracing_enabled() if self._enabled is None else self._enabled

    @contextmanager
    def span(self, name: str, attributes: Optional[Mapping[str, Any]] = None,
             parent: Optional[Span] = None) -> Iterator[Span]:
        if not self.enabled:
            yield _NOOP_SPAN
            return
        parent = parent or _current_span.get()
        span = Span(
            name=name,
            trace_id=parent.trace_id if parent else secrets.token_hex(16),
            span_id=secrets.token_hex(8),
            parent_id=parent.span_id if parent else None,
            start_ns=time.time_ns(),
            attributes=dict(attributes or {}),
        )
        token = _current_span.set(span)
        try:
            yield span
        except BaseException as exc:
            span.record_exception(exc)
            raise
        finally:
            span.end_ns = time.time_ns()
            _current_span.reset(token)
            # tail-drop health probes by name OR http.path attribute
            haystack = span.name + " " + str(span.attributes.get("http.path", ""))
            if not any(s in haystack for s in _drop_name_substrings):
                _exporter.export(span)

    @contextmanager
    def start_as_current_span(self, name: str, **kw: Any) -> Iterator[Span]:
        with self.span(name, **kw) as s:
            yield s


_NOOP_SPAN = Span(name="noop", trace_id="0" * 32, span_id="0" * 16)
_tracers: Dict[str, Tracer] = {}


def get_tracer(name: str) -> Tracer:
    if name not in _tracers:
        _tracers[name] = Tracer(name)
    return _tracers[name]


def current_span() -> Optional[Span]:
    return _current_span.get()


@contextmanager
def stage_span(name: str, tracer_name: str = "rag") -> Iterator[Span]:
    """Span + latency histogram for one RAG pipeline stage.

    The pipelined dataplane needs per-stage visibility (embed / retrieve /
    rerank / generate) on BOTH surfaces: the span lands in whatever exporter
    is configured (child of the enclosing chain span, so stage waterfalls
    show up in Jaeger), and the wall time lands in a ``stage_<name>_s``
    histogram (core/metrics.py) that /metrics and bench.py read even when
    tracing is disabled."""
    from generativeaiexamples_tpu.core.metrics import REGISTRY

    t0 = time.perf_counter()
    try:
        with get_tracer(tracer_name).span(f"{tracer_name}:{name}") as span:
            rid = _request_id.get()
            if rid and span is not _NOOP_SPAN:
                # the X-Request-Id of the request this stage serves — the
                # join key across spans, /debug/requests timelines, and
                # SLO breach records (never stamped on the shared no-op)
                span.set_attribute("request_id", rid)
            yield span
    finally:
        REGISTRY.histogram(f"stage_{name}_s").observe(
            time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# W3C TraceContext propagation (ref: tracing.py:46 TraceContextTextMapPropagator)
# ---------------------------------------------------------------------------

def inject_traceparent(headers: Dict[str, str],
                       span: Optional[Span] = None) -> Dict[str, str]:
    """Stamp the W3C ``traceparent`` for ``span`` (explicit — a manually
    managed span, see :func:`start_span`) or the ambient current span."""
    span = span if span is not None else _current_span.get()
    if span is not None and tracing_enabled():
        headers["traceparent"] = f"00-{span.trace_id}-{span.span_id}-01"
    return headers


def start_span(name: str, attributes: Optional[Mapping[str, Any]] = None,
               parent: Optional[Span] = None) -> Optional[Span]:
    """Manually-managed span for generator/streaming call sites where a
    ``with`` block cannot scope the work (the failover router's streamed
    chat lives across many ``yield``s — a context manager there would leak
    the contextvar into the consumer between resumptions). Returns None
    when tracing is disabled; close with :func:`end_span`. The span is
    NOT installed as the ambient current span — propagate it explicitly
    via ``inject_traceparent(headers, span=...)``."""
    if not tracing_enabled():
        return None
    parent = parent if parent is not None else _current_span.get()
    return Span(
        name=name,
        trace_id=parent.trace_id if parent else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
        parent_id=parent.span_id if parent else None,
        start_ns=time.time_ns(),
        attributes=dict(attributes or {}),
    )


def end_span(span: Optional[Span]) -> None:
    """Finish + export a :func:`start_span` span (same health-probe tail
    filter as the context-manager path). None is a no-op, so call sites
    need no tracing-enabled guard of their own."""
    if span is None:
        return
    span.end_ns = time.time_ns()
    haystack = span.name + " " + str(span.attributes.get("http.path", ""))
    if not any(s in haystack for s in _drop_name_substrings):
        _exporter.export(span)


def extract_traceparent(headers: Mapping[str, str]) -> Optional[Span]:
    """Parse an incoming ``traceparent`` into a synthetic parent span
    (ref: llamaindex_instrumentation_wrapper extracting ctx from HTTP headers,
    tracing.py:62-73)."""
    raw = headers.get("traceparent")
    if raw is None:  # HTTP header names are case-insensitive on the wire
        for key, value in headers.items():
            if key.lower() == "traceparent":
                raw = value
                break
    if not raw:
        return None
    parts = raw.split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, _ = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    return Span(name="remote-parent", trace_id=trace_id, span_id=span_id)


@contextmanager
def use_parent(span: Optional[Span]) -> Iterator[None]:
    """Attach an extracted remote parent for the duration of a request."""
    if span is None:
        yield
        return
    token = _current_span.set(span)
    try:
        yield
    finally:
        _current_span.reset(token)
