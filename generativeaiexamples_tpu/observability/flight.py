"""Engine flight recorder + per-request timeline log.

Post-incident "why did tok/s crater at 14:02" questions need continuous
per-step engine state, not uptime-averaged counters (RAGO's per-stage
characterization argument, PAPERS.md). Two bounded in-memory stores, both
strictly memory-capped, both free when nobody reads them:

  * ``FLIGHT`` — a ring buffer of scheduler-step samples (decode batch
    fill, waiting/prefilling/running queue depths, KV pages free/used,
    prefix-cache hit tokens, preemptions, tok/s between samples). The
    scheduler feeds it time-gated (``maybe_sample``, default every 250 ms),
    so the driver loop pays one clock read per tick when a sample is not
    due. Numeric fields are mirrored into ``flight_*`` gauges
    (core/metrics.py), so the *current* engine state also rides ``/metrics``.
    Dump surfaces: ``GET /debug/flight?window=<s>`` (server/common.py) and
    SIGUSR1 → JSON file (``install_signal_dump``).

  * ``REQUEST_LOG`` — the last N finished requests' timelines
    (queued → admitted → prefill_start → first_token → finished, plus
    preemption count, prefix-hit tokens, finish cause), looked up by
    request id via ``GET /debug/requests/<id>`` and stamped onto the engine
    server's spans. Phase stamps all come from the injected perf clock
    (core/clock.py — ``time.perf_counter`` live, virtual under the
    simulator), so phase ordering is exact; ``finished_unix`` anchors the
    timeline to the wall clock for cross-log correlation.

Env knobs: ``APP_FLIGHT_CAPACITY`` (samples, default 4096),
``APP_FLIGHT_INTERVAL_MS`` (default 250 — ~17 min of history at the
default capacity), ``APP_FLIGHT_DUMP_PATH`` (SIGUSR1 target, default
``/tmp/flight_<pid>.json``), ``APP_REQUEST_LOG_CAPACITY`` (default 512).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Mapping, Optional

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.core.metrics import REGISTRY

logger = logging.getLogger(__name__)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _policy_state() -> Dict[str, Any]:
    """Point-in-time QoS virtual-time state + KV-tier occupancy for the
    crash-dump artifact. Both planes live in the engine package, whose
    import pulls jax — a process that never loaded it (router, encoder)
    CANNOT have registered either object, so consult sys.modules instead
    of importing (the /debug/qos handler's idiom, server/common.py)."""
    import sys
    out: Dict[str, Any] = {}
    qos_mod = sys.modules.get("generativeaiexamples_tpu.engine.qos")
    if qos_mod is not None:
        try:
            out["qos"] = qos_mod.debug_payload()
        except Exception:
            logger.exception("flight dump: qos snapshot failed")
    tier_mod = sys.modules.get("generativeaiexamples_tpu.engine.kv_tier")
    if tier_mod is not None:
        try:
            out["kv_tier"] = tier_mod.occupancy_payload()
        except Exception:
            logger.exception("flight dump: kv-tier snapshot failed")
    # the event-trace tail rides the dump too: a post-incident artifact
    # should carry the last decisions, not just the last gauges
    try:
        from generativeaiexamples_tpu.observability.trace import TRACE
        out["trace"] = {**TRACE.describe(),
                        "tail": TRACE.window(600.0, limit=512)}
    except Exception:
        logger.exception("flight dump: trace tail failed")
    return out


class FlightRecorder:
    """Bounded ring of engine-state samples with time-gated capture."""

    def __init__(self, capacity: Optional[int] = None,
                 interval_s: Optional[float] = None) -> None:
        self.capacity = capacity if capacity is not None else _env_int(
            "APP_FLIGHT_CAPACITY", 4096)
        self.interval_s = interval_s if interval_s is not None else (
            _env_float("APP_FLIGHT_INTERVAL_MS", 250.0) / 1000.0)
        self._ring: Deque[Dict[str, Any]] = deque(maxlen=max(1, self.capacity))
        # discrete incidents (recompiles, resets) keep their OWN bounded
        # ring: periodic samples share a fixed field shape that window
        # consumers (bench percentiles, dashboards) iterate uniformly, and
        # an event sample interleaved among them would break that contract
        self._events: Deque[Dict[str, Any]] = deque(maxlen=256)
        self._lock = threading.Lock()
        self._last_t = 0.0
        self._prev: Optional[Dict[str, Any]] = None

    def maybe_sample(self, fields_fn: Callable[[], Mapping[str, Any]]) -> bool:
        """Record a sample iff the interval has elapsed. ``fields_fn`` is
        only invoked when a sample is due — the fast path is one clock
        read, cheap enough for every scheduler tick."""
        now = clock.mono()
        if now - self._last_t < self.interval_s:
            return False
        with self._lock:
            if now - self._last_t < self.interval_s:
                return False
            self._last_t = now
        self.record(**dict(fields_fn()))
        return True

    def record(self, **fields: Any) -> Dict[str, Any]:
        """Unconditionally append one sample; derives ``tok_s`` from the
        ``tokens_generated`` delta against the previous sample and mirrors
        numeric fields into ``flight_*`` gauges.

        Each sample carries two stamps: ``ts`` (wall clock — what dumps,
        bench windows, and cross-log correlation key on) and ``mono``
        (monotonic — what every delta and window cutoff computes from, so
        an NTP step can never produce a negative tok/s or swallow a
        window)."""
        now = clock.mono()
        sample: Dict[str, Any] = {"ts": clock.wall(), "mono": now}
        sample.update(fields)
        with self._lock:
            prev = self._prev
            if prev is not None and "tokens_generated" in fields:
                dt = now - prev["mono"]
                if dt > 1e-6:
                    sample["tok_s"] = round(
                        (fields["tokens_generated"]
                         - prev.get("tokens_generated", 0)) / dt, 2)
            self._prev = sample
            self._ring.append(sample)
        for key, value in fields.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            REGISTRY.gauge(f"flight_{key}").set(value)
        return sample

    def event(self, name: str, **fields: Any) -> Dict[str, Any]:
        """Record a discrete incident — a mid-serving recompile, a pool
        reset — into the event ring (``/debug/flight`` serves it next to
        the sample window; SIGUSR1 dumps carry it). Events bypass the time
        gate and never touch the periodic ring or its tok/s delta chain:
        sample consumers iterate a fixed field shape that an interleaved
        event would break."""
        sample: Dict[str, Any] = {"ts": clock.wall(), "mono": clock.mono(),
                                  "event": name}
        sample.update(fields)
        with self._lock:
            self._events.append(sample)
        return sample

    def events(self, seconds: Optional[float] = None) -> List[Dict[str, Any]]:
        """Events from the last ``seconds`` (None = whole ring), oldest
        first."""
        with self._lock:
            events = list(self._events)
        if seconds is not None:
            cutoff = clock.mono() - seconds
            events = [e for e in events if e["mono"] >= cutoff]
        return events

    def window(self, seconds: Optional[float] = None,
               limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Samples from the last ``seconds`` (None = whole ring), oldest
        first; ``limit`` keeps only the NEWEST n of them (a capped debug
        poll wants the most recent state, not the window's head)."""
        with self._lock:
            samples = list(self._ring)
        if seconds is not None:
            cutoff = clock.mono() - seconds
            samples = [s for s in samples if s["mono"] >= cutoff]
        if limit is not None and len(samples) > limit:
            samples = samples[len(samples) - limit:]
        return samples

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._events.clear()
            self._prev = None
            self._last_t = 0.0

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            n_events = len(self._events)
        return {"capacity": self.capacity,
                "interval_s": self.interval_s,
                "samples_held": len(self),
                "events_held": n_events}

    def dump(self, path: str) -> str:
        """Write the full ring as JSON (the SIGUSR1 / post-incident dump),
        plus the QoS virtual-time state, KV-tier occupancy, and the event
        trace's recent tail when those planes are loaded — the crash
        artifact answers "what was the policy state" without a second
        probe of a possibly-dead server."""
        payload = {"dumped_at_unix": clock.wall(), **self.describe(),
                   "samples": self.window(), "events": self.events(),
                   **_policy_state()}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(payload, fh)
        return path


FLIGHT = FlightRecorder()


# ---------------------------------------------------------------------------
# Per-request timelines
# ---------------------------------------------------------------------------

_PHASES = ("queued", "admitted", "prefill_start", "first_token", "finished")


def timeline(req: Any) -> Dict[str, Any]:
    """Serializable timeline of a scheduler Request. Phase values share the
    injected perf clock (monotonic ordering is exact); unreached
    phases (e.g. a request failed before admission) are omitted."""
    stamps = {
        "queued": getattr(req, "submitted_at", None),
        "admitted": getattr(req, "admitted_at", None),
        "prefill_start": getattr(req, "prefill_start_at", None),
        "first_token": getattr(req, "first_token_at", None),
        "finished": getattr(req, "finished_at", None),
    }
    phases = {k: round(v, 6) for k, v in stamps.items() if v is not None}
    out: Dict[str, Any] = {
        "request_id": getattr(req, "request_id", ""),
        "phases": phases,
        "preemptions": getattr(req, "preemptions", 0),
        # resume-mode split (live migration + host spill, engine/spill.py):
        # of the preemptions/evacuations this request survived, how many
        # recovered by TRANSFER (spill promote, snapshot resume) — the
        # rest recomputed via re-prefill. Recompute-vs-transfer recovery
        # is visible per request, not just in fleet counters.
        "spill_resumes": getattr(req, "spill_resumes", 0),
        "snapshot_resumes": getattr(req, "snapshot_resumes", 0),
        "prefix_hit_tokens": getattr(req, "prefix_hit_tokens", 0),
        # prefix-tier split (engine/kv_tier.py): how many of the prefix
        # hits were promoted from the HOST tier (vs device prefix cache)
        "tier_hit_tokens": getattr(req, "tier_hit_tokens", 0),
        "completion_tokens": getattr(req, "completion_tokens", 0),
        "prompt_tokens": len(getattr(req, "prompt_ids", []) or []),
        "finish": getattr(req, "finish_reason", None),
        "error": getattr(req, "error", None),
        # usage plane (observability/usage.py): the tenant the request
        # billed to — /debug/requests timelines join /debug/usage rows
        "tenant": getattr(req, "tenant", "") or "anon",
        # SLO plane (observability/slo.py): the scheduler judges attainment
        # BEFORE recording, so timelines, breach records, and
        # slo_requests_total agree per request
        "slo_class": getattr(req, "slo_class", None),
        "slo": getattr(req, "slo", None),
        "finished_unix": clock.wall(),
    }
    durations: Dict[str, float] = {}
    q = stamps["queued"]
    if q is not None:
        for phase, key in (("admitted", "queue_wait_s"),
                           ("first_token", "ttft_s"),
                           ("finished", "total_s")):
            if stamps[phase] is not None:
                durations[key] = round(stamps[phase] - q, 6)
    if stamps["prefill_start"] is not None and stamps["first_token"] is not None:
        durations["prefill_to_first_token_s"] = round(
            stamps["first_token"] - stamps["prefill_start"], 6)
    out["durations_s"] = durations
    return out


def timeline_attributes(req: Any) -> Dict[str, Any]:
    """Flat span attributes for a finished request (engine/server.py stamps
    these on its per-request span)."""
    rec = timeline(req)
    attrs: Dict[str, Any] = {
        "request.id": rec["request_id"],
        "request.preemptions": rec["preemptions"],
        "request.prefix_hit_tokens": rec["prefix_hit_tokens"],
        "request.tier_hit_tokens": rec["tier_hit_tokens"],
        "request.completion_tokens": rec["completion_tokens"],
        "request.finish": rec["finish"] or (rec["error"] and "error") or "",
    }
    for key, value in rec["durations_s"].items():
        attrs[f"request.{key}"] = value
    return attrs


class RequestLog:
    """Bounded id-addressable log of recent request timelines."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = capacity if capacity is not None else _env_int(
            "APP_REQUEST_LOG_CAPACITY", 512)
        self._recs: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()

    def record(self, req: Any) -> Dict[str, Any]:
        rec = timeline(req)
        rid = rec["request_id"]
        with self._lock:
            self._recs.pop(rid, None)
            self._recs[rid] = rec
            while len(self._recs) > max(1, self.capacity):
                self._recs.popitem(last=False)
        return rec

    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._recs.get(request_id)

    def recent(self, n: int = 50) -> List[Dict[str, Any]]:
        """Newest first."""
        with self._lock:
            recs = list(self._recs.values())
        return recs[::-1][:max(0, n)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)

    def clear(self) -> None:
        with self._lock:
            self._recs.clear()


REQUEST_LOG = RequestLog()


# ---------------------------------------------------------------------------
# SIGUSR1 → dump-to-file
# ---------------------------------------------------------------------------

_signal_installed = False


def install_signal_dump(path: Optional[str] = None) -> bool:
    """``kill -USR1 <pid>`` dumps the flight ring to a JSON file — the
    no-endpoint escape hatch for a wedged or unreachable server. Only
    installable from the main thread (signal module constraint); returns
    False (with a log line) anywhere it cannot install, so server startup
    never fails on it."""
    global _signal_installed
    if _signal_installed:
        return True
    target = (path or os.environ.get("APP_FLIGHT_DUMP_PATH", "")
              or f"/tmp/flight_{os.getpid()}.json")

    def _handler(signum: int, frame: Any) -> None:
        try:
            FLIGHT.dump(target)
            logger.info("flight recorder dumped to %s (%d samples)",
                        target, len(FLIGHT))
        except OSError as exc:
            logger.warning("flight dump to %s failed: %s", target, exc)

    try:
        import signal
        if threading.current_thread() is not threading.main_thread():
            raise ValueError("not in main thread")
        signal.signal(signal.SIGUSR1, _handler)
    except (ValueError, AttributeError, OSError) as exc:
        logger.info("SIGUSR1 flight dump not installed: %s", exc)
        return False
    _signal_installed = True
    logger.info("SIGUSR1 dumps flight recorder to %s", target)
    return True
