"""SLO & goodput plane: deadline accounting, burn-rate alerts, shed signals.

PR 2 gave the stack raw telemetry (flight ring, timelines, Prometheus
metrics) but nothing *interprets* it. This module adds the judgment layer
RAGO (arxiv 2503.14649) argues RAG serving is actually governed by — per-
stage TTFT/TPOT budgets, not raw throughput — with NinjaLLM's (arxiv
2407.12057) headline metric, SLO attainment, measured per request:

  * **SLO classes** (``interactive`` / ``batch`` / ``best_effort``)
    declared in config (``APP_SLO_*``, core/config.py) with TTFT, TPOT and
    end-to-end budgets and a ``sheddable`` bit.
  * **Deadline accounting**: the chain server stamps a class + deadline at
    admission (:func:`admission`); outbound LLM calls propagate the
    *remaining* budget to the engine as ``X-Request-Class`` /
    ``X-Request-Deadline-Ms`` headers (:func:`outbound_headers`,
    chains/llm_client.py) — remaining-ms, not absolute time, so two
    processes never need agreeing clocks.
  * **Attainment judging** (:meth:`SloTracker.observe`): every finished
    request is judged from its PR-2 timeline stamps (submitted → first
    token → finished) against its class budgets; the verdict is stamped on
    the request (so ``/debug/requests/<id>`` timelines carry it), counted
    into ``slo_requests_total{class,outcome}``, and observed into
    per-class latency histograms that carry the request's trace id as an
    OpenMetrics exemplar (core/metrics.py) — a breach on ``/debug/slo``
    links straight to its trace.
  * **Multi-window burn-rate alerts** (:meth:`SloTracker.pressure`):
    Google-SRE-style paired windows (default 5 m fast / 1 h slow) over the
    class error budget, all window math on an injected monotonic clock
    (deterministic under test, tpulint clock-discipline by construction).
    ``pressure() ∈ {ok, warn, critical}`` fires only when BOTH windows
    burn past the paired threshold; ``best_effort``'s own breaches are
    excluded from the signal (shedding it must not keep pressure high).
  * **Shed signal**: the engine scheduler consults ``pressure()`` each
    admission pass and sheds pending ``sheddable``-class requests under
    ``critical`` (engine/scheduler.py); server/failover.py reads the
    pressure each worker reports on ``/health``.

Everything is process-global (``SLO``) like REGISTRY/FLIGHT; servers dump
the full picture at ``GET /debug/slo`` (server/common.py).
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Any, Callable, Deque, Dict, Iterator, List, Mapping, Optional, Tuple

from generativeaiexamples_tpu.core.metrics import REGISTRY

CLASS_HEADER = "X-Request-Class"
DEADLINE_HEADER = "X-Request-Deadline-Ms"
# Client-facing aliases (PR 15): direct engine clients and bench drive
# /v1/chat/completions and /v1/kv/prefill without the chain server
# fronting them — the short names are the documented public contract,
# the X-Request-* pair stays the internal propagation form (canonical
# headers win when both arrive). Both servers parse both; outbound
# propagation emits both so the router forwards deadline stamping to
# engines reading either name.
CLASS_HEADER_ALIAS = "X-Slo-Class"
DEADLINE_HEADER_ALIAS = "X-Deadline-Ms"

_PRESSURE_LEVELS = ("ok", "warn", "critical")


@dataclass(frozen=True)
class SLOClass:
    """One serving objective: latency budgets + shed policy."""

    name: str
    ttft_s: float
    tpot_s: float
    e2e_s: float
    sheddable: bool = False


def _classes_from_config() -> Tuple[Dict[str, SLOClass], Dict[str, Any]]:
    """(classes, evaluator knobs) from the APP_SLO_* config section."""
    from generativeaiexamples_tpu.core.config import get_config

    slo = get_config().slo
    classes = {}
    for name in ("interactive", "batch", "best_effort"):
        c = getattr(slo, name)
        classes[name] = SLOClass(name=name, ttft_s=c.ttft_s, tpot_s=c.tpot_s,
                                 e2e_s=c.e2e_s, sheddable=c.sheddable)
    knobs = {"default_class": slo.default_class, "target": slo.target,
             "fast_window_s": slo.fast_window_s,
             "slow_window_s": slo.slow_window_s,
             "warn_burn": slo.warn_burn, "critical_burn": slo.critical_burn,
             "min_events": slo.min_events}
    return classes, knobs


class _BucketWindow:
    """Good/bad event counts bucketed on a monotonic clock.

    Fixed-width buckets (fast_window / 30) in a bounded deque covering the
    slow window; summing a window is O(buckets) — cheap enough to run on
    every (cached) pressure evaluation, and the memory bound is static.
    """

    def __init__(self, bucket_s: float, span_s: float) -> None:
        self.bucket_s = max(1e-6, bucket_s)
        self._buckets: Deque[List[float]] = deque(
            maxlen=max(2, int(span_s / self.bucket_s) + 1))

    def add(self, now: float, good: int = 0, bad: int = 0) -> None:
        start = now - (now % self.bucket_s)
        if self._buckets and self._buckets[-1][0] == start:
            self._buckets[-1][1] += good
            self._buckets[-1][2] += bad
        else:
            self._buckets.append([start, float(good), float(bad)])

    def totals(self, now: float, window_s: float) -> Tuple[float, float]:
        """(good, bad) inside the trailing ``window_s``."""
        cutoff = now - window_s
        good = bad = 0.0
        for start, g, b in reversed(self._buckets):
            if start + self.bucket_s <= cutoff:
                break
            good += g
            bad += b
        return good, bad


class SloTracker:
    """Process-wide SLO state: per-class attainment, burn rates, pressure.

    ``clock`` must be monotonic (tests inject a fake); wall time appears
    only as a reported timestamp on breach records.
    """

    BREACH_LOG = 64

    def __init__(self, classes: Optional[Mapping[str, SLOClass]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 **knobs: Any) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._configured = classes is not None
        self._classes: Dict[str, SLOClass] = dict(classes or {})
        self._knobs: Dict[str, Any] = dict(knobs)
        self._windows: Dict[str, _BucketWindow] = {}
        self._breaches: Deque[Dict[str, Any]] = deque(maxlen=self.BREACH_LOG)
        self._pressure = "ok"
        self._pressure_at: Optional[float] = None
        self._pressure_ttl = 1.0   # re-evaluate at most once per second
        # external hazards (devtime's mid-serving recompile watch): each
        # floors pressure at "warn" until its TTL — a latency cliff becomes
        # an alert BEFORE the burn windows can even see its breaches
        self._hazards: Deque[Dict[str, Any]] = deque(maxlen=32)
        self._hazard_until: Optional[float] = None

    # ------------------------------------------------------------ config

    def _ensure_config(self) -> None:
        if self._configured:
            return
        classes, knobs = _classes_from_config()
        with self._lock:
            if not self._configured:
                self._classes = classes
                knobs.update(self._knobs)   # explicit ctor knobs win
                self._knobs = knobs
                self._configured = True

    def knob(self, name: str) -> Any:
        self._ensure_config()
        return self._knobs[name]

    def classes(self) -> Dict[str, SLOClass]:
        self._ensure_config()
        return dict(self._classes)

    def default_class(self) -> str:
        return str(self.knob("default_class"))

    def resolve(self, name: Optional[str]) -> SLOClass:
        """Class by name; empty/None → the configured default. Unknown
        names raise KeyError — the serving layer maps that to a 400."""
        self._ensure_config()
        return self._classes[name or self.default_class()]

    def reset(self) -> None:
        """Drop accumulated state (tests; config is re-read lazily)."""
        with self._lock:
            self._windows.clear()
            self._breaches.clear()
            self._pressure = "ok"
            self._pressure_at = None
            self._hazards.clear()
            self._hazard_until = None

    # ------------------------------------------------------------ hazards

    def note_hazard(self, kind: str, detail: Optional[Dict[str, Any]] = None,
                    warn_for_s: float = 60.0) -> None:
        """Record an external hazard — an event that predicts imminent
        breaches before any request has actually missed its budget (the
        devtime compile-watch reports mid-serving XLA recompiles here).
        Pressure is floored at ``warn`` for ``warn_for_s`` so routers and
        dashboards see the cliff as it happens; ``critical`` (shedding)
        still requires real measured burn."""
        now = self._clock()
        with self._lock:
            self._hazards.append({"ts_unix": time.time(), "kind": kind,
                                  "detail": dict(detail or {})})
            until = now + max(0.0, warn_for_s)
            if self._hazard_until is None or until > self._hazard_until:
                self._hazard_until = until
            self._pressure_at = None   # next pressure() re-evaluates
        REGISTRY.counter("slo_hazards_total", labels={"kind": kind}).inc()

    # ------------------------------------------------------------ judging

    def judge(self, req: Any) -> Dict[str, Any]:
        """Attainment verdict for a finished scheduler Request (or any
        object with the PR-2 timeline attributes). Pure — no counters.

        Outcomes: ``attained`` | ``breached`` (with per-dimension detail)
        | ``error`` (failed before completing) | ``shed`` (preset by the
        scheduler's load shedder). All durations difference stamps from
        one monotonic clock (Request uses perf_counter throughout).
        """
        preset = getattr(req, "slo_outcome", None)
        cls = self.resolve_or_default(getattr(req, "slo_class", None))
        verdict: Dict[str, Any] = {"class": cls.name}
        if preset == "shed":
            verdict["outcome"] = "shed"
            return verdict
        if getattr(req, "error", None):
            verdict["outcome"] = "error"
            return verdict
        submitted = getattr(req, "submitted_at", None)
        first = getattr(req, "first_token_at", None)
        finished = getattr(req, "finished_at", None)
        ntok = getattr(req, "completion_tokens", 0) or 0
        breaches: Dict[str, Dict[str, float]] = {}
        if submitted is not None and first is not None:
            ttft = first - submitted
            verdict["ttft_s"] = round(ttft, 6)
            if ttft > cls.ttft_s:
                breaches["ttft"] = {"observed_s": round(ttft, 6),
                                    "budget_s": cls.ttft_s}
        if first is not None and finished is not None and ntok > 1:
            tpot = (finished - first) / (ntok - 1)
            verdict["tpot_s"] = round(tpot, 6)
            if tpot > cls.tpot_s:
                breaches["tpot"] = {"observed_s": round(tpot, 6),
                                    "budget_s": cls.tpot_s}
        if submitted is not None and finished is not None:
            e2e = finished - submitted
            verdict["e2e_s"] = round(e2e, 6)
            budget = cls.e2e_s
            deadline = getattr(req, "deadline_s", None)
            if deadline is not None:
                budget = min(budget, deadline)
            if e2e > budget:
                breaches["e2e"] = {"observed_s": round(e2e, 6),
                                   "budget_s": round(budget, 6)}
        if breaches:
            verdict["outcome"] = "breached"
            verdict["breaches"] = breaches
        else:
            verdict["outcome"] = "attained"
        return verdict

    def resolve_or_default(self, name: Optional[str]) -> SLOClass:
        try:
            return self.resolve(name)
        except KeyError:
            return self.resolve(None)

    def observe(self, req: Any) -> Dict[str, Any]:
        """Judge a finished request and account it: stamps ``req.slo``
        (REQUEST_LOG.record then persists it into the timeline), counts
        ``slo_requests_total{class,outcome}``, feeds the burn windows, logs
        breaches, and observes per-class latency histograms carrying the
        request's trace id as an exemplar."""
        verdict = self.judge(req)
        try:
            req.slo = verdict
        except AttributeError:
            pass   # SimpleNamespace-style fakes always accept; slots won't
        cls, outcome = verdict["class"], verdict["outcome"]
        REGISTRY.counter("slo_requests_total",
                         labels={"class": cls, "outcome": outcome}).inc()
        exemplar = None
        trace_id = getattr(req, "trace_id", "") or ""
        if trace_id:
            exemplar = {"trace_id": trace_id}
        for dim in ("ttft", "tpot", "e2e"):
            value = verdict.get(f"{dim}_s")
            if value is not None:
                REGISTRY.histogram(f"slo_{dim}_s",
                                   labels={"class": cls}).observe(
                    value, exemplar=exemplar)
        now = self._clock()
        counted = outcome in ("attained", "breached", "error")
        with self._lock:
            if counted:
                self._window(cls).add(now, good=int(outcome == "attained"),
                                      bad=int(outcome != "attained"))
            if outcome == "breached":
                self._breaches.append({
                    "ts_unix": time.time(),
                    "request_id": getattr(req, "request_id", ""),
                    "trace_id": trace_id,
                    "class": cls,
                    "breaches": verdict.get("breaches", {}),
                })
        return verdict

    def _window(self, cls: str) -> _BucketWindow:
        # caller holds self._lock
        if cls not in self._windows:
            fast = float(self.knob("fast_window_s"))
            slow = float(self.knob("slow_window_s"))
            self._windows[cls] = _BucketWindow(bucket_s=fast / 30.0,
                                               span_s=slow)
        return self._windows[cls]

    # ------------------------------------------------------------ burn rate

    def burn_rates(self, cls: str) -> Dict[str, float]:
        """{fast, slow} burn rates for one class: (error rate) / (error
        budget). 1.0 = burning exactly the budget; 10 = 10x too fast."""
        self._ensure_config()
        now = self._clock()
        budget = max(1e-9, 1.0 - float(self.knob("target")))
        out = {}
        with self._lock:
            win = self._windows.get(cls)
            for key in ("fast", "slow"):
                span = float(self.knob(f"{key}_window_s"))
                good, bad = win.totals(now, span) if win else (0.0, 0.0)
                total = good + bad
                rate = (bad / total) if total else 0.0
                out[key] = round(rate / budget, 4)
                out[f"{key}_events"] = int(total)
        return out

    def pressure(self) -> str:
        """Current shed signal, re-evaluated at most once per second
        (cached on the injected clock — the scheduler consults this every
        admission pass). A level fires only when BOTH windows of some
        non-sheddable class burn past its paired threshold and the fast
        window has seen ``min_events`` requests."""
        self._ensure_config()
        now = self._clock()
        with self._lock:
            if (self._pressure_at is not None
                    and now - self._pressure_at < self._pressure_ttl):
                return self._pressure
        level = "ok"
        for name, cls in self.classes().items():
            if cls.sheddable:
                continue    # shedding best_effort must not sustain pressure
            rates = self.burn_rates(name)
            if rates["fast_events"] < int(self.knob("min_events")):
                continue
            for cand, knob in (("critical", "critical_burn"),
                               ("warn", "warn_burn")):
                threshold = float(self.knob(knob))
                if rates["fast"] >= threshold and rates["slow"] >= threshold:
                    if (_PRESSURE_LEVELS.index(cand)
                            > _PRESSURE_LEVELS.index(level)):
                        level = cand
                    break
        with self._lock:
            if (level == "ok" and self._hazard_until is not None
                    and now < self._hazard_until):
                level = "warn"   # active hazard floors pressure (note_hazard)
            self._pressure = level
            self._pressure_at = now
        REGISTRY.gauge("slo_pressure").set(_PRESSURE_LEVELS.index(level))
        return level

    # ------------------------------------------------------------ reporting

    def debug_payload(self) -> Dict[str, Any]:
        """The ``GET /debug/slo`` body: per-class budgets, window
        attainment, burn rates, pressure, recent breaches."""
        self._ensure_config()
        pressure = self.pressure()
        per_class = {}
        for name, cls in self.classes().items():
            rates = self.burn_rates(name)
            fast_events = rates.pop("fast_events")
            slow_events = rates.pop("slow_events")
            snap = REGISTRY.counter("slo_requests_total",
                                    labels={"class": name,
                                            "outcome": "attained"}).value
            total = snap
            for outcome in ("breached", "error", "shed"):
                total += REGISTRY.counter(
                    "slo_requests_total",
                    labels={"class": name, "outcome": outcome}).value
            per_class[name] = {
                "budgets": {"ttft_s": cls.ttft_s, "tpot_s": cls.tpot_s,
                            "e2e_s": cls.e2e_s},
                "sheddable": cls.sheddable,
                "burn_rate": rates,
                "window_events": {"fast": fast_events, "slow": slow_events},
                "lifetime": {"total": total, "attained": snap,
                             "attainment": (round(snap / total, 4)
                                            if total else None)},
            }
        with self._lock:
            breaches = list(self._breaches)[::-1]
            hazards = list(self._hazards)[::-1]
            hazard_active = (self._hazard_until is not None
                             and self._clock() < self._hazard_until)
        return {
            "pressure": pressure,
            "hazard_active": hazard_active,
            "recent_hazards": hazards,
            "target": float(self.knob("target")),
            "windows_s": {"fast": float(self.knob("fast_window_s")),
                          "slow": float(self.knob("slow_window_s"))},
            "thresholds": {"warn": float(self.knob("warn_burn")),
                           "critical": float(self.knob("critical_burn"))},
            "default_class": self.default_class(),
            "classes": per_class,
            "recent_breaches": breaches,
        }


SLO = SloTracker()


# ---------------------------------------------------------------------------
# Admission context + header propagation (chain → engine)
# ---------------------------------------------------------------------------

@dataclass
class _Admission:
    slo_class: str
    deadline_mono: float     # absolute on time.monotonic


_admission: contextvars.ContextVar[Optional[_Admission]] = \
    contextvars.ContextVar("gaie_tpu_slo_admission", default=None)


@contextmanager
def admission(slo_class: Optional[str] = None,
              deadline_ms: Optional[float] = None) -> Iterator[_Admission]:
    """Stamp the current request's SLO class + deadline for downstream LLM
    calls (the chain server enters this around chain execution; LocalLLM /
    RemoteLLM / FailoverLLM read it via :func:`current_admission` /
    :func:`outbound_headers`). ``deadline_ms`` is REMAINING budget — an
    inbound ``X-Request-Deadline-Ms`` rides through shrinking, never a
    wall-clock instant."""
    cls = SLO.resolve_or_default(slo_class)
    budget_s = cls.e2e_s if deadline_ms is None else deadline_ms / 1000.0
    adm = _Admission(slo_class=cls.name,
                     deadline_mono=time.monotonic() + budget_s)
    token = _admission.set(adm)
    try:
        yield adm
    finally:
        _admission.reset(token)


def current_admission() -> Optional[_Admission]:
    return _admission.get()


def remaining_s(adm: Optional[_Admission] = None) -> Optional[float]:
    adm = adm if adm is not None else _admission.get()
    if adm is None:
        return None
    return adm.deadline_mono - time.monotonic()


def outbound_headers(headers: Optional[Dict[str, str]] = None
                     ) -> Dict[str, str]:
    """Class + remaining-deadline headers for an outbound engine call,
    injected alongside the W3C traceparent (chains/llm_client.py attaches
    these to every /v1 request)."""
    from generativeaiexamples_tpu.observability import otel

    headers = headers if headers is not None else {}
    otel.inject_traceparent(headers)
    adm = _admission.get()
    if adm is not None:
        headers[CLASS_HEADER] = adm.slo_class
        headers[CLASS_HEADER_ALIAS] = adm.slo_class
        rem = remaining_s(adm)
        deadline_ms = str(max(0, int(rem * 1000)))
        headers[DEADLINE_HEADER] = deadline_ms
        headers[DEADLINE_HEADER_ALIAS] = deadline_ms
    return headers


def parse_inbound(headers: Mapping[str, str],
                  fallback_class: Optional[str] = None
                  ) -> Tuple[Optional[str], Optional[float]]:
    """(slo_class, deadline_s) from propagated admission headers — the one
    parser both servers share (engine/server.py maps failures to 400,
    server/api.py to 422). An unknown class is a loud ValueError: silently
    downgrading a caller's objective would falsify every attainment number
    downstream. ``fallback_class`` lets the chain server accept a body
    field when no header is present."""
    # public aliases (X-Slo-Class / X-Deadline-Ms) parse wherever the
    # canonical internal pair does — direct engine clients and bench get
    # deadline stamping without the chain server fronting them; canonical
    # wins when both arrive
    cls = ((headers.get(CLASS_HEADER) or "").strip()
           or (headers.get(CLASS_HEADER_ALIAS) or "").strip()
           or (fallback_class or "").strip() or None)
    if cls is not None:
        try:
            SLO.resolve(cls)
        except KeyError:
            raise ValueError(f"unknown SLO class {cls!r}; declared: "
                             f"{sorted(SLO.classes())}")
    deadline_s = None
    raw = ((headers.get(DEADLINE_HEADER) or "").strip()
           or (headers.get(DEADLINE_HEADER_ALIAS) or "").strip())
    if raw:
        try:
            deadline_s = max(0.0, float(raw) / 1000.0)
        except ValueError:
            raise ValueError(f"{DEADLINE_HEADER} must be milliseconds, "
                             f"got {raw!r}")
    return cls, deadline_s


def stamp_request(req: Any, slo_class: Optional[str] = None,
                  deadline_s: Optional[float] = None) -> None:
    """Stamp class/deadline onto a scheduler Request at submission. Explicit
    args (HTTP headers, engine/server.py) win; otherwise the ambient
    admission context (LocalLLM in-process path); otherwise the default
    class with its full e2e budget."""
    adm = _admission.get()
    if slo_class is None and adm is not None:
        slo_class = adm.slo_class
        if deadline_s is None:
            deadline_s = remaining_s(adm)
    cls = SLO.resolve_or_default(slo_class)
    req.slo_class = cls.name
    req.deadline_s = cls.e2e_s if deadline_s is None else deadline_s
