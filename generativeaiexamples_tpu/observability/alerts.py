"""SLO burn-rate alerting — multi-window rules over the judge's verdicts.

Classic SRE burn-rate alerting (fast window catches cliffs, slow window
confirms sustained burn; both must exceed the threshold) applied to the
three serving objectives the SLO judge already scores on every finished
request: **goodput** (attained vs everything else), **TTFT** and **TPOT**
(per-objective budget breaches). Rules are evaluated per SLO class and
per tenant (tenant scopes fold to ``other`` past a small cap — the
metric-cardinality rule applies here too).

Burn rate = (bad / total) / error_budget where error_budget =
``1 - target``; a rate of 1.0 spends the budget exactly over the window.
Thresholds, window widths and the attainment target come from the same
``APP_SLO_*`` knobs the judge uses (``SLO.knob``) — one vocabulary, no
second config surface.

Raise/clear edges publish everywhere the house already looks:
``alert_active{alert,severity}`` gauges, ``alerts_fired_total{severity}``
counters, FLIGHT events, and ``slo.note_hazard`` so QoS pressure
coupling fires before goodput craters. ``GET /debug/alerts``
(server/common.py) serves the live payload on every server.

Feeding happens inside ``FORENSICS.observe`` on scheduler finish paths,
so ``APP_FORENSICS=off`` means zero alert-plane calls (the zero-overhead
pattern, test-enforced). Clock discipline: core/clock.py only — the
tpulint clock-injection rule covers this module, and an injected clock
lets tests hand-compute both windows.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from generativeaiexamples_tpu.core import clock
from generativeaiexamples_tpu.core.metrics import REGISTRY
from generativeaiexamples_tpu.observability import slo as slo_mod
from generativeaiexamples_tpu.observability.flight import FLIGHT
from generativeaiexamples_tpu.observability.lockwatch import tracked_lock
from generativeaiexamples_tpu.observability.slo import _BucketWindow

OBJECTIVES = ("goodput", "ttft", "tpot")

_TENANT_CAP = 8           # distinct tenant scopes before folding to other
_FIRED_LOG = 128
_EVAL_TTL_S = 1.0


def _is_bad(objective: str, verdict: Dict[str, Any]) -> bool:
    outcome = verdict.get("outcome", "")
    if objective == "goodput":
        return outcome != "attained"
    if outcome in ("shed",):
        return False              # shed requests never saw a first token
    breaches = verdict.get("breaches") or {}
    return bool(breaches.get(objective)) or outcome == "error"


class AlertManager:
    """Process-global burn-rate evaluator (``ALERTS``).

    One ``_BucketWindow`` per (objective, scope); scopes are
    ``class:<slo class>`` and ``tenant:<tenant>``. Evaluation is cached
    for ``_EVAL_TTL_S`` on the injected clock so the finish path never
    pays more than a dict walk per second.
    """

    def __init__(self, clock_fn: Optional[Callable[[], float]] = None,
                 **knobs: Any) -> None:
        self._clock = clock_fn or clock.mono
        self._knobs: Dict[str, Any] = dict(knobs)
        self._lock = tracked_lock("alerts._lock")
        self._windows: Dict[Tuple[str, str], _BucketWindow] = {}
        self._active: Dict[str, Dict[str, Any]] = {}
        self._fired: Deque[Dict[str, Any]] = deque(maxlen=_FIRED_LOG)
        self._last_eval: Optional[float] = None

    def _knob(self, name: str) -> Any:
        if name in self._knobs:
            return self._knobs[name]
        return slo_mod.SLO.knob(name)

    def reset(self) -> None:
        with self._lock:
            for rec in self._active.values():
                REGISTRY.gauge("alert_active",
                               labels={"alert": rec["alert"],
                                       "severity": rec["severity"]}).set(0)
            self._windows.clear()
            self._active.clear()
            self._fired.clear()
            self._last_eval = None

    # ------------------------------------------------------------- feed

    def _scopes(self, verdict: Dict[str, Any], req: Any) -> List[str]:
        cls = str(verdict.get("class", "") or "")
        tenant = str(getattr(req, "tenant", "") or "anon")
        scopes = []
        if cls:
            scopes.append("class:" + cls)
        with self._lock:
            known = {s for (_, s) in self._windows
                     if s.startswith("tenant:")}
        tscope = "tenant:" + tenant
        if tscope not in known and len(known) >= _TENANT_CAP:
            tscope = "tenant:other"
        scopes.append(tscope)
        return scopes

    def _window(self, objective: str, scope: str) -> _BucketWindow:
        key = (objective, scope)
        win = self._windows.get(key)
        if win is None:
            fast = float(self._knob("fast_window_s"))
            slow = float(self._knob("slow_window_s"))
            win = _BucketWindow(bucket_s=fast / 30.0, span_s=slow)
            self._windows[key] = win
        return win

    def observe(self, req: Any, verdict: Dict[str, Any]) -> None:
        """Feed one finished request's verdict into every matching
        (objective, scope) window, then (TTL-cached) re-evaluate."""
        if not verdict:
            return
        now = self._clock()
        scopes = self._scopes(verdict, req)
        with self._lock:
            for objective in OBJECTIVES:
                bad = _is_bad(objective, verdict)
                for scope in scopes:
                    self._window(objective, scope).add(
                        now, good=int(not bad), bad=int(bad))
        self.evaluate()

    # ------------------------------------------------------- evaluation

    def _burn(self, win: _BucketWindow, now: float,
              window_s: float, budget: float) -> Tuple[float, float]:
        good, bad = win.totals(now, window_s)
        total = good + bad
        if total <= 0:
            return 0.0, 0.0
        return (bad / total) / budget, total

    def evaluate(self, force: bool = False) -> List[Dict[str, Any]]:
        """Walk every window pair; raise/clear edges on threshold
        crossings. Returns the active alert list."""
        now = self._clock()
        with self._lock:
            if (not force and self._last_eval is not None
                    and now - self._last_eval < _EVAL_TTL_S):
                return list(self._active.values())
            self._last_eval = now
            keys = list(self._windows)
        fast_s = float(self._knob("fast_window_s"))
        slow_s = float(self._knob("slow_window_s"))
        budget = max(1e-9, 1.0 - float(self._knob("target")))
        min_events = int(self._knob("min_events"))
        thresholds = (("critical", float(self._knob("critical_burn"))),
                      ("warn", float(self._knob("warn_burn"))))
        raised, cleared = [], []
        with self._lock:
            for objective, scope in keys:
                win = self._windows[(objective, scope)]
                fast_burn, fast_n = self._burn(win, now, fast_s, budget)
                slow_burn, _ = self._burn(win, now, slow_s, budget)
                severity = ""
                if fast_n >= min_events:
                    for cand, thr in thresholds:
                        if fast_burn >= thr and slow_burn >= thr:
                            severity = cand
                            break
                name = f"{objective}:{scope}"
                rec = self._active.get(name)
                if severity:
                    row = {"alert": name, "severity": severity,
                           "objective": objective, "scope": scope,
                           "fast_burn": round(fast_burn, 3),
                           "slow_burn": round(slow_burn, 3),
                           "since_mono": rec["since_mono"] if rec
                           else round(now, 3)}
                    if rec is None or rec["severity"] != severity:
                        raised.append((row, rec))
                    self._active[name] = row
                elif rec is not None:
                    del self._active[name]
                    cleared.append(rec)
            active = list(self._active.values())
        for row, prev in raised:
            self._publish_raise(row, prev)
        for rec in cleared:
            self._publish_clear(rec)
        return active

    def _publish_raise(self, row: Dict[str, Any],
                       prev: Optional[Dict[str, Any]]) -> None:
        if prev is not None:          # severity change: drop the old gauge
            REGISTRY.gauge("alert_active",
                           labels={"alert": prev["alert"],
                                   "severity": prev["severity"]}).set(0)
        REGISTRY.gauge("alert_active",
                       labels={"alert": row["alert"],
                               "severity": row["severity"]}).set(1)
        REGISTRY.counter("alerts_fired_total",
                         labels={"severity": row["severity"]}).inc()
        FLIGHT.event("alert_raised", alert=row["alert"],
                     severity=row["severity"], fast_burn=row["fast_burn"],
                     slow_burn=row["slow_burn"])
        with self._lock:
            self._fired.append(dict(row))
        try:
            slo_mod.SLO.note_hazard(
                "alert:" + row["objective"],
                {"alert": row["alert"], "severity": row["severity"],
                 "fast_burn": row["fast_burn"]},
                warn_for_s=float(self._knob("fast_window_s")))
        except Exception:   # tpulint: disable=except-swallow -- the hazard coupling is best-effort; the alert itself already published
            pass

    def _publish_clear(self, rec: Dict[str, Any]) -> None:
        REGISTRY.gauge("alert_active",
                       labels={"alert": rec["alert"],
                               "severity": rec["severity"]}).set(0)
        FLIGHT.event("alert_cleared", alert=rec["alert"],
                     severity=rec["severity"])

    # ------------------------------------------------------ read surface

    def active(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._active.values()]

    def fired(self) -> List[Dict[str, Any]]:
        """Raise-edge log, oldest-first (bench round JSON)."""
        with self._lock:
            return [dict(r) for r in self._fired]

    def payload(self) -> Dict[str, Any]:
        """GET /debug/alerts body."""
        active = self.evaluate()
        return {
            "active": active,
            "fired_total": len(self.fired()),
            "recent_fired": self.fired()[-8:],
            "objectives": list(OBJECTIVES),
            "rules": {
                "windows_s": {"fast": float(self._knob("fast_window_s")),
                              "slow": float(self._knob("slow_window_s"))},
                "thresholds": {
                    "warn": float(self._knob("warn_burn")),
                    "critical": float(self._knob("critical_burn"))},
                "target": float(self._knob("target")),
                "min_events": int(self._knob("min_events")),
            },
        }


ALERTS = AlertManager()
