"""Observability: in-tree tracing SDK, span exporters, system metrics.

Replaces the reference's OTel-SDK + collector + Jaeger sidecar stack
(ref: RAG/tools/observability/, RAG/src/chain_server/tracing.py) with a
self-contained span model: same trace/span semantics and W3C TraceContext
propagation, exporters pluggable (console, in-memory for tests, JSONL file).

Sibling planes: ``flight`` (scheduler-state ring + request timelines),
``slo`` (budgets, burn rates, shed/hazard pressure), ``devtime`` (the
per-dispatch device-time ledger + compile-watch — which program burned the
chip, live), ``usage`` (the per-tenant cost-attribution ledger — who spent
it, fleet-wide), ``profiling`` (jax device traces).
"""

from generativeaiexamples_tpu.observability.bootstrap import (  # noqa: F401
    init_observability,
)
from generativeaiexamples_tpu.observability.devtime import (  # noqa: F401
    DEVTIME,
    DevtimeLedger,
)
from generativeaiexamples_tpu.observability.flight import (  # noqa: F401
    FLIGHT,
    REQUEST_LOG,
    FlightRecorder,
    RequestLog,
    install_signal_dump,
    timeline,
    timeline_attributes,
)
from generativeaiexamples_tpu.observability.usage import (  # noqa: F401
    USAGE,
    UsageLedger,
    tenant_from_headers,
)
from generativeaiexamples_tpu.observability.otel import (  # noqa: F401
    ConsoleSpanExporter,
    InMemorySpanExporter,
    JsonlSpanExporter,
    Span,
    Tracer,
    extract_traceparent,
    get_tracer,
    inject_traceparent,
    set_exporter,
)
