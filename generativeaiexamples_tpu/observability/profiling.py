"""Device-side profiling hooks — jax.profiler traces as the TPU analogue of
the reference's span-based observability (SURVEY §5.1: "same OTel span model
in the serving layer + jax.profiler traces (Perfetto/TensorBoard) for
device-side profiling").

Spans (observability/otel.py) explain *where a request spent time* across
the pipeline; these traces explain *what the chip did* during that time —
XLA op timelines, HBM pressure, fusion boundaries. Two entry points:

  * `profile_trace(log_dir)` — context manager around any region (a bench
    phase, one engine dispatch, an ingest batch); writes a TensorBoard/
    Perfetto-loadable trace directory.
  * `start_server(port)` — the live sampling endpoint TensorBoard's profile
    plugin connects to (`localhost:<port>`), for profiling a serving
    process under real load without code changes.

Both are thin but load-bearing: they gate every use behind availability
checks so CPU-only test environments and stripped jax builds degrade to
no-ops with a log line instead of crashing the serving path.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Iterator, Optional

logger = logging.getLogger(__name__)

_server_started = False


def start_server(port: int = 9012) -> bool:
    """Start jax's profiler server once per process; TensorBoard's profile
    plugin (or `xprof`) captures from it on demand."""
    global _server_started
    if _server_started:
        return True
    try:
        import jax

        jax.profiler.start_server(port)
    except Exception as exc:  # stripped builds / port in use
        logger.warning("profiler server unavailable: %s", exc)
        return False
    _server_started = True
    logger.info("jax profiler server listening on localhost:%d", port)
    return True


@contextlib.contextmanager
def profile_trace(log_dir: str, host_tracer_level: int = 2
                  ) -> Iterator[Optional[str]]:
    """Trace the enclosed region into ``log_dir`` (TensorBoard: point the
    profile plugin at it; Perfetto: load the .trace.json.gz inside).

    Yields the concrete trace directory (timestamped, one per entry) or
    None when tracing is unavailable — callers never need their own guard.
    """
    try:
        import jax

        run_dir = os.path.join(log_dir, time.strftime("trace_%Y%m%d_%H%M%S"))
        jax.profiler.start_trace(run_dir,
                                 create_perfetto_trace=False)
    except Exception as exc:
        logger.warning("profiler trace unavailable: %s", exc)
        yield None
        return
    try:
        yield run_dir
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as exc:
            logger.warning("profiler stop failed: %s", exc)


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named sub-region inside an active trace (shows up as a track event
    on the device timeline) — the device-side sibling of an OTel span.

    Annotation setup is guarded SEPARATELY from the caller's body: a
    ``try`` spanning the ``yield`` would catch exceptions the caller's own
    code raises through it, yield a second time, and make contextlib
    replace the caller's real error with "generator didn't stop after
    throw()"."""
    annotation = None
    try:
        import jax

        annotation = jax.profiler.TraceAnnotation(name)
        annotation.__enter__()
    except Exception as exc:   # stripped builds / no active trace backend
        logger.debug("device trace annotation %r unavailable: %s", name, exc)
        annotation = None
    try:
        yield
    finally:
        if annotation is not None:
            try:
                annotation.__exit__(None, None, None)
            except Exception as exc:
                logger.debug("trace annotation %r close failed: %s",
                             name, exc)
