"""Runtime lock-order sanitizer — the dynamic counterpart of tpulint's
static ``lock-order`` rule (analysis/callgraph.py, docs/static_analysis.md).

The static rule proves ordering over call chains it can RESOLVE; anything
wired through a callback, a thread boundary, or a data structure is
invisible to pure AST analysis (the kv_tier→qos victim-bias callback was
exactly such an edge before PR 18 removed it). This module closes that
gap at runtime: the serving plane's locks are constructed through
:func:`tracked_lock` / :func:`tracked_rlock`, and while armed every
*blocking* acquisition is recorded into a witness order graph — lock A
held while lock B is acquired adds edge ``A → B``. The first acquisition
that would close a cycle is reported as an **inversion** with BOTH
witness stacks (the acquisition that created the conflicting edge and
the one that closed the cycle), which is the full deadlock diagnosis: no
need to actually deadlock, one interleaving of each order suffices.

Gating is the house zero-overhead pattern (``APP_LOCKWATCH=off|on``,
default off, the ``APP_DEVTIME`` shape): when off the factories return
**raw** ``threading.Lock``/``RLock`` objects — not a pass-through
wrapper, the real primitive — so the serving hot path pays literally
nothing, a property the test suite enforces by counting watch calls over
a real scheduler tick. The env is re-read at every construction, so a
test (or the fuzz harness) arming ``APP_LOCKWATCH=on`` before building a
Scheduler gets tracked locks without touching module import order.

Also watched: holds longer than ``APP_LOCKWATCH_HOLD_MS`` (default 100)
are recorded with the holder's stack — a long hold under the scheduler
lock is the latency smoking gun even when ordering is clean. The whole
payload is served by ``GET /debug/locks`` (server/common.py) and
asserted empty by the scheduler fuzz/chaos suites, which double as a
1000-episode deadlock hunt.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional, Tuple, Union

_DEF_HOLD_MS = 100.0


def _env_on() -> bool:
    return (os.environ.get("APP_LOCKWATCH", "").strip().lower()
            in ("on", "1", "true"))


def _stack(skip: int = 2, limit: int = 10) -> List[str]:
    """Trimmed caller stack, innermost last — ``skip`` drops the
    lockwatch frames themselves so reports start at the acquire site."""
    frames = traceback.extract_stack()[:-skip]
    return [f"{f.filename}:{f.lineno} in {f.name}" for f in frames[-limit:]]


class LockWatch:
    """Process-global witness graph (``WATCH``). Internal state is
    guarded by a single RAW lock — the watcher must never watch itself."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tls = threading.local()
        self.hold_ms = float(
            os.environ.get("APP_LOCKWATCH_HOLD_MS", "") or _DEF_HOLD_MS)
        # (held, acquired) -> first witness: both stacks + thread name
        self._edges: Dict[Tuple[str, str], dict] = {}
        self._adj: Dict[str, set] = {}
        self._locks_seen: set = set()
        self._inversions: List[dict] = []
        self._long_holds: "deque[dict]" = deque(maxlen=256)

    # -- per-thread held stack -------------------------------------------

    def _held(self) -> List[dict]:
        h = getattr(self._tls, "held", None)
        if h is None:
            h = self._tls.held = []
        return h

    # -- recording -------------------------------------------------------

    def note_acquired(self, name: str, blocking: bool) -> None:
        """Called AFTER the underlying acquire succeeds. Reentrant
        re-acquisition (RLock) bumps a depth counter and adds no edges —
        re-entry cannot deadlock against itself."""
        held = self._held()
        for entry in held:
            if entry["name"] == name:
                entry["depth"] += 1
                return
        stack = _stack(skip=3)
        if blocking:
            # only a BLOCKING acquire can participate in a deadlock, but
            # the locks already held count however they were acquired
            for entry in held:
                self._note_edge(entry, name, stack)
        held.append({"name": name, "t0": time.monotonic(),
                     "stack": stack, "depth": 1})
        with self._mu:
            self._locks_seen.add(name)

    def note_released(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            entry = held[i]
            if entry["name"] != name:
                continue
            entry["depth"] -= 1
            if entry["depth"] > 0:
                return
            del held[i]
            held_ms = (time.monotonic() - entry["t0"]) * 1000.0
            if held_ms > self.hold_ms:
                with self._mu:
                    self._long_holds.append({
                        "lock": name,
                        "held_ms": round(held_ms, 3),
                        "thread": threading.current_thread().name,
                        "stack": entry["stack"],
                    })
            return

    def _note_edge(self, held_entry: dict, acquired: str,
                   acquire_stack: List[str]) -> None:
        a, b = held_entry["name"], acquired
        if a == b:
            return
        with self._mu:
            witness = {
                "held": a,
                "acquired": b,
                "thread": threading.current_thread().name,
                "held_stack": held_entry["stack"],
                "acquire_stack": acquire_stack,
            }
            if (a, b) not in self._edges:
                self._edges[(a, b)] = witness
                self._adj.setdefault(a, set()).add(b)
            # would this edge close a cycle?  walk b -> ... -> a
            path = self._find_path(b, a)
            if path is not None:
                conflict = self._edges.get((path[0], path[1]))
                self._inversions.append({
                    "cycle": [a] + path,
                    "this": witness,
                    "conflict": conflict,
                })

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS in the witness graph; returns ``[src, ..., dst]`` or None.
        Caller holds ``self._mu``."""
        seen = {src}
        stack: List[Tuple[str, List[str]]] = [(src, [src])]
        while stack:
            node, path = stack.pop()
            for nxt in self._adj.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- read surface ----------------------------------------------------

    @property
    def inversions(self) -> List[dict]:
        with self._mu:
            return list(self._inversions)

    def payload(self) -> dict:
        """The /debug/locks body: the whole witness graph plus every
        inversion and long hold observed since arming."""
        with self._mu:
            return {
                "enabled": True,
                "hold_ms": self.hold_ms,
                "locks": sorted(self._locks_seen),
                "edges": [
                    {"held": a, "acquired": b, "thread": w["thread"]}
                    for (a, b), w in sorted(self._edges.items())
                ],
                "inversions": list(self._inversions),
                "long_holds": list(self._long_holds),
            }

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._adj.clear()
            self._locks_seen.clear()
            self._inversions.clear()
            self._long_holds.clear()


WATCH = LockWatch()


class TrackedLock:
    """Wrapper around ``threading.Lock``/``RLock`` reporting every
    acquisition to :data:`WATCH`. Only constructed while the watch is
    armed — the off path never sees this class."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner: Union[threading.Lock, type(None)]
                 = None, reentrant: bool = False) -> None:
        self.name = name
        self._inner = inner if inner is not None else (
            threading.RLock() if reentrant else threading.Lock())

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            WATCH.note_acquired(self.name, blocking)
        return got

    def release(self) -> None:
        WATCH.note_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TrackedLock({self.name!r})"


def tracked_lock(name: str) -> Union[threading.Lock, TrackedLock]:
    """A ``threading.Lock`` for ``name`` — RAW when ``APP_LOCKWATCH`` is
    off (zero overhead, enforced by test), tracked when armed. The env
    is read per construction, not per module import."""
    if not _env_on():
        return threading.Lock()
    return TrackedLock(name)


def tracked_rlock(name: str) -> Union[threading.RLock, TrackedLock]:
    """Reentrant variant — re-acquisition by the owner records no edge."""
    if not _env_on():
        return threading.RLock()
    return TrackedLock(name, reentrant=True)
