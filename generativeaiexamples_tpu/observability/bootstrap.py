"""One-call observability activation for server startup.

The pieces all exist individually — env-selected span exporters
(otel.configure_from_env), the jax profiler server (profiling.start_server),
the SIGUSR1 flight dump (flight.install_signal_dump) — but nothing in the
serving path activated them: a process started with
``APP_TRACING_EXPORTER=otlp APP_PROFILER_PORT=9012`` exported nothing and
listened nowhere. Every server entrypoint (engine, encoder, chain) calls
``init_observability()`` before binding its port.

Env surface (all opt-in; absent vars are no-ops):

  * ``APP_TRACING_EXPORTER`` (+ ``APP_TRACING_OTLP_ENDPOINT`` /
    ``APP_TRACING_JSONL_PATH`` / ``APP_TRACING_SERVICE``) — span exporter;
  * ``ENABLE_TRACING`` — actually emit spans (exporter alone is inert);
  * ``APP_PROFILER_PORT`` — jax profiler server for live TensorBoard/xprof
    capture (0/empty = off);
  * ``APP_FLIGHT_DUMP_PATH`` — SIGUSR1 flight-dump target.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_done = False


def init_observability(service: str = "") -> None:
    """Idempotent; safe from any server's startup path."""
    global _done
    if _done:
        return
    _done = True
    from generativeaiexamples_tpu.observability import flight, otel, profiling

    if service and not os.environ.get("APP_TRACING_SERVICE"):
        os.environ["APP_TRACING_SERVICE"] = f"generativeaiexamples-tpu-{service}"
    exporter = otel.configure_from_env()
    if exporter is not None:
        logger.info("tracing exporter: %s", type(exporter).__name__)
    raw_port = os.environ.get("APP_PROFILER_PORT", "").strip()
    if raw_port:
        try:
            port = int(raw_port)
        except ValueError:
            logger.warning("APP_PROFILER_PORT=%r is not an int; profiler "
                           "server not started", raw_port)
            port = 0
        if port > 0:
            profiling.start_server(port)
    flight.install_signal_dump()
