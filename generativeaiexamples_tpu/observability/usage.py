"""Fleet usage plane: who spent the chip, per tenant, across workers.

ROADMAP item 4 asks for per-tenant token-rate quotas and weighted fair
queuing — but quotas need something to enforce AGAINST, and until this
module the tree had no tenant concept at all.  Meanwhile the disaggregated
route (PR 6) spans router + prefill + decode processes whose telemetry
never met: each worker answers ``/metrics`` alone, so "what did this
tenant cost the fleet" was unanswerable.  RAGO (arxiv 2503.14649) frames
serving optimization as a measured search — this is the measurement half
the future scheduler/affinity work enforces against, the same way the
PR 9 devtime ledger powered the PR 11 roofline campaign.

One process-global ledger (``USAGE``), three layers:

  * **Identity.**  A request's tenant comes from the ``X-Tenant-Id``
    header (or a stable hash of its API key; default ``"anon"``),
    sanitized to a label-safe token.  The failover router forwards the
    header on EVERY dispatch of a logical request — the prefill→handoff
    pair included — and the KV-handoff payload carries it too, so one
    logical chat bills its prefill-worker and decode-replica device time
    to the same tenant.  A contextvar (:func:`set_tenant` /
    :func:`tenant_scope`) propagates the identity through the chain
    server's sync generators onto the router's outbound headers.

  * **Billing.**  The scheduler bills every finished (or failed) request
    a resource vector: queue seconds, prefill/decode device-seconds
    (joined from the DEVTIME per-dispatch ledger by prorating each
    program family's timed device seconds over its useful tokens —
    :meth:`DevtimeLedger.phase_rates`; when ``APP_DEVTIME=off`` leaves no
    timed samples the vector falls back to token counts as the cost
    proxy, ``basis: "tokens"``), tokens in/out, KV **page-seconds**
    (pages held × wall seconds, stamped in scheduler.py at
    alloc/grow/release/export), prefix-hit tokens, and router-side
    retries/hedges.  Per-tenant Prometheus families
    (``usage_requests_total{tenant=...}`` and kin) ride ``/metrics``.

  * **Bounded cardinality.**  Label values are where metrics registries
    die: tenant ids are caller-controlled strings, so the ledger admits
    at most ``APP_USAGE_MAX_TENANTS`` distinct tenants (default 64) and
    folds the rest into the ``"other"`` bucket — test-enforced, and the
    tpulint ``metric-cardinality`` rule guards the same failure mode
    tree-wide.

Surfaces: ``GET /debug/usage`` (this process), the compact
``usage_by_tenant`` rollup riding every engine ``/health`` body (the
probe cycle the router already runs), and the router's ``GET
/debug/fleet`` (per-worker role/occupancy/MFU/prefix-hit/watchdog cards
plus the fleet-summed tenant rollups — see server/failover.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import re
import threading
import time
from typing import Any, Dict, Iterable, Mapping, Optional

from generativeaiexamples_tpu.core.config import env_int
from generativeaiexamples_tpu.core.metrics import REGISTRY

DEFAULT_TENANT = "anon"
OVERFLOW_TENANT = "other"

# label-safe tenant tokens: the id becomes a Prometheus label value and a
# JSON key on several debug surfaces — never trusted further than that
_TENANT_RE = re.compile(r"[^A-Za-z0-9_.:\-]")
_TENANT_MAX_LEN = 64


def sanitize_tenant(raw: Any) -> str:
    """Normalize a caller-supplied tenant id to a label-safe token;
    empty/None → ``""`` (callers choose their own default).  A caller
    CLAIMING a sentinel name (``other``/``anon``) is escaped with a
    ``t_`` prefix: real traffic must never alias the ledger's overflow/
    default buckets — a customer named "other" would otherwise absorb
    every folded tenant's bills (and vice versa).  Escaping happens at
    this one extraction boundary, so the identity stays stable across
    the handoff payload round-trip (idempotent re-sanitization)."""
    if raw is None:
        return ""
    tenant = _TENANT_RE.sub("", str(raw).strip())[:_TENANT_MAX_LEN]
    if tenant in (OVERFLOW_TENANT, DEFAULT_TENANT):
        return "t_" + tenant
    return tenant


def tenant_from_headers(headers: Mapping[str, str],
                        default: str = DEFAULT_TENANT) -> str:
    """Extract the request's tenant identity from HTTP headers.

    ``X-Tenant-Id`` wins (the explicit contract, and what the failover
    router stamps on every dispatch).  Without it, an API key
    (``Authorization: Bearer …`` / ``X-Api-Key``) identifies the tenant
    as a short stable blake2b digest — the raw key must never become a
    metric label or debug-surface string.  Neither present → ``default``.
    """
    explicit = sanitize_tenant(headers.get("X-Tenant-Id"))
    if explicit:
        return explicit
    key = (headers.get("Authorization") or headers.get("X-Api-Key")
           or "").strip()
    if key:
        if key.lower().startswith("bearer "):
            key = key[7:].strip()
        if key:
            return "key-" + hashlib.blake2b(
                key.encode("utf-8"), digest_size=5).hexdigest()
    return default


def handoff_tenant(headers: Mapping[str, str],
                   payload: Mapping[str, Any]) -> str:
    """Tenant identity for a KV-handoff admission — one logical chat must
    bill ONE tenant across the disaggregated route, so precedence is:
    explicit ``X-Tenant-Id`` header (the router forwards it on every
    dispatch) → the tenant the prefill worker stamped into the payload →
    API-key hash / ``anon``.  The key hash ranks BELOW the payload tenant
    here (unlike plain endpoints): an auth-fronted decode worker must not
    split the chat's legs across two tenant keys."""
    return (sanitize_tenant(headers.get("X-Tenant-Id"))
            or sanitize_tenant(payload.get("tenant"))
            or tenant_from_headers(headers))


# --------------------------------------------------------------------------
# contextvar propagation (chain server → router outbound headers)
# --------------------------------------------------------------------------

_TENANT_CTX: contextvars.ContextVar[str] = contextvars.ContextVar(
    "usage_tenant", default="")


def set_tenant(tenant: str) -> contextvars.Token:
    return _TENANT_CTX.set(sanitize_tenant(tenant))


def reset_tenant(token: contextvars.Token) -> None:
    _TENANT_CTX.reset(token)


def current_tenant() -> str:
    """The ambient tenant identity ("" when none was admitted) — the
    router reads this onto its outbound ``X-Tenant-Id`` header."""
    return _TENANT_CTX.get()


@contextlib.contextmanager
def tenant_scope(tenant: str):
    token = set_tenant(tenant)
    try:
        yield
    finally:
        reset_tenant(token)


# --------------------------------------------------------------------------
# the ledger
# --------------------------------------------------------------------------

_VECTOR_FIELDS = (
    "requests", "errors", "queue_s", "prefill_device_s", "decode_device_s",
    "tokens_in", "tokens_out", "prefix_hit_tokens", "kv_page_s",
    "retries", "hedges", "handoffs",
)


class _TenantVector:
    """Accumulated resource vector for one tenant."""

    __slots__ = _VECTOR_FIELDS + ("first_seen_unix",)

    def __init__(self) -> None:
        for f in _VECTOR_FIELDS:
            setattr(self, f, 0.0)
        self.first_seen_unix = time.time()

    _COUNT_FIELDS = frozenset({"requests", "errors", "tokens_in",
                               "tokens_out", "prefix_hit_tokens", "retries",
                               "hedges", "handoffs"})

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for f in _VECTOR_FIELDS:
            v = getattr(self, f)
            out[f] = int(v) if f in self._COUNT_FIELDS else round(v, 6)
        out["device_s"] = round(self.prefill_device_s
                                + self.decode_device_s, 6)
        out["first_seen_unix"] = round(self.first_seen_unix, 3)
        return out


class UsageLedger:
    """Process-global per-tenant usage ledger (see module doc).

    Thread-safety: billed from the engine driver thread, router chat
    threads, and test harnesses; one lock guards the tenant map.  Metric
    emission happens outside the lock (REGISTRY has its own locks).
    """

    def __init__(self, max_tenants: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantVector] = {}
        self._max = max(1, max_tenants if max_tenants is not None
                        else env_int("APP_USAGE_MAX_TENANTS", 64))
        self._overflowed = 0        # bill events folded into "other"

    @property
    def max_tenants(self) -> int:
        return self._max

    def configure(self, max_tenants: Optional[int] = None) -> None:
        """Runtime override (tests, bench)."""
        with self._lock:
            if max_tenants is not None:
                self._max = max(1, int(max_tenants))

    def reset(self) -> None:
        """Drop accumulated vectors (tests, bench phases). The Prometheus
        families keep their lifetime values — counters are monotonic."""
        with self._lock:
            self._tenants.clear()
            self._overflowed = 0

    # ----------------------------------------------------------- admission

    def _vec_locked(self, tenant: str):
        """Caller holds the lock. Admits a tenant key, folding NEW tenants
        past the cardinality cap into the overflow bucket — the label
        space on /metrics and every rollup surface stays bounded no
        matter how many distinct ids callers mint.  Returns the
        ``(canonical_key, vector)`` pair so metric labels and ledger rows
        can never disagree."""
        vec = self._tenants.get(tenant)
        if vec is not None:
            return tenant, vec
        if (len(self._tenants) >= self._max
                and tenant not in (OVERFLOW_TENANT, DEFAULT_TENANT)):
            self._overflowed += 1
            tenant = OVERFLOW_TENANT
            vec = self._tenants.get(tenant)
            if vec is not None:
                return tenant, vec
        vec = self._tenants[tenant] = _TenantVector()
        return tenant, vec

    def canonical(self, tenant: Any) -> str:
        """The key a bill for ``tenant`` would land under RIGHT NOW
        (sanitized; overflow-folded past the cap) — what metric labels
        use, so labels and ledger rows can never disagree."""
        t = sanitize_tenant(tenant) or DEFAULT_TENANT
        with self._lock:
            if t in self._tenants or len(self._tenants) < self._max \
                    or t in (OVERFLOW_TENANT, DEFAULT_TENANT):
                return t
        return OVERFLOW_TENANT

    # ------------------------------------------------------------- billing

    def bill_request(self, req: Any) -> str:
        """Bill one finished (or failed) scheduler Request; returns the
        canonical tenant key it landed under.  Called by the scheduler
        BEFORE the request log write and the stream release, so a client
        that reads ``[DONE]`` and immediately polls ``/debug/usage``
        finds its own request already billed.

        Device-seconds join the DEVTIME ledger by proration: each program
        family's timed seconds-per-useful-token rate × this request's
        tokens.  A request admitted via KV handoff (``kv_import_s`` set)
        bills NO prompt tokens and no prefill seconds — its prefill
        worker already billed them, so the fleet-summed vector counts
        each logical chat's prompt exactly once.
        """
        tenant = sanitize_tenant(getattr(req, "tenant", "")) or DEFAULT_TENANT
        imported = getattr(req, "kv_import_s", None) is not None
        prompt_toks = 0 if imported else len(
            getattr(req, "prompt_ids", []) or [])
        out_toks = int(getattr(req, "completion_tokens", 0) or 0)
        hit_toks = int(getattr(req, "prefix_hit_tokens", 0) or 0)
        page_s = float(getattr(req, "kv_page_seconds", 0.0) or 0.0)
        sub = getattr(req, "submitted_at", None)
        adm = getattr(req, "admitted_at", None)
        queue_s = max(0.0, adm - sub) if (sub is not None
                                          and adm is not None) else 0.0
        rates = _phase_rates()
        pf_rate = rates.get("prefill")
        dc_rate = rates.get("decode")
        # prefix-cache hits skipped prefill compute — only the recomputed
        # suffix bills prefill device time
        pf_s = ((prompt_toks - min(hit_toks, prompt_toks)) * pf_rate
                if pf_rate is not None else 0.0)
        dc_s = out_toks * dc_rate if dc_rate is not None else 0.0
        err = bool(getattr(req, "error", None))
        handoff = getattr(req, "finish_reason", None) == "handoff"
        with self._lock:
            key, vec = self._vec_locked(tenant)
            vec.requests += 1
            vec.errors += 1 if err else 0
            vec.queue_s += queue_s
            vec.prefill_device_s += pf_s
            vec.decode_device_s += dc_s
            vec.tokens_in += prompt_toks
            vec.tokens_out += out_toks
            vec.prefix_hit_tokens += hit_toks
            vec.kv_page_s += page_s
            vec.handoffs += 1 if handoff else 0
        # bounded-label Prometheus families, outside the lock
        REGISTRY.counter("usage_requests_total",
                         labels={"tenant": key}).inc()
        if prompt_toks:
            REGISTRY.counter("usage_tokens_total",
                             labels={"tenant": key, "dir": "in"}
                             ).inc(prompt_toks)
        if out_toks:
            REGISTRY.counter("usage_tokens_total",
                             labels={"tenant": key, "dir": "out"}
                             ).inc(out_toks)
        if pf_s:
            REGISTRY.counter("usage_device_seconds",
                             labels={"tenant": key, "phase": "prefill"}
                             ).inc(pf_s)
        if dc_s:
            REGISTRY.counter("usage_device_seconds",
                             labels={"tenant": key, "phase": "decode"}
                             ).inc(dc_s)
        if page_s:
            REGISTRY.counter("usage_kv_page_seconds",
                             labels={"tenant": key}).inc(page_s)
        return key

    def _bump(self, field: str, tenant: Optional[str]) -> str:
        t = sanitize_tenant(tenant) if tenant else current_tenant()
        t = t or DEFAULT_TENANT
        with self._lock:
            key, vec = self._vec_locked(t)
            setattr(vec, field, getattr(vec, field) + 1)
        return key

    def bill_retry(self, tenant: Optional[str] = None) -> None:
        """Count a router retry against the (ambient) tenant — retries
        burn fleet capacity even when the request eventually succeeds."""
        key = self._bump("retries", tenant)
        REGISTRY.counter("usage_retries_total", labels={"tenant": key}).inc()

    def bill_hedge(self, tenant: Optional[str] = None) -> None:
        """Count a launched hedge leg (a deliberate duplicate dispatch)."""
        key = self._bump("hedges", tenant)
        REGISTRY.counter("usage_hedges_total", labels={"tenant": key}).inc()

    # ----------------------------------------------------------- reporting

    def rollup(self) -> Dict[str, Dict[str, float]]:
        """Compact per-tenant rollup riding the engine ``/health`` body —
        the piggyback on the router's existing probe cycle, so keep it
        small: one short-keyed dict per tenant.  Rows are built UNDER the
        lock (as snapshot() does): a concurrent bill must never leak a
        half-applied vector (requests bumped, tokens not yet) into the
        fleet view."""
        with self._lock:
            return {
                t: {
                    "req": int(v.requests),
                    "tok_in": int(v.tokens_in),
                    "tok_out": int(v.tokens_out),
                    "device_s": round(v.prefill_device_s
                                      + v.decode_device_s, 4),
                    "queue_s": round(v.queue_s, 4),
                    "kv_page_s": round(v.kv_page_s, 4),
                    "prefix_hit_tok": int(v.prefix_hit_tokens),
                }
                for t, v in self._tenants.items()
            }

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /debug/usage`` body: full vectors, cap state, and the
        billing basis (``devtime`` when the DEVTIME ledger holds timed
        samples to prorate, ``tokens`` when the off mode left only token
        counts)."""
        rates = _phase_rates()
        with self._lock:
            tenants = {t: v.snapshot() for t, v in self._tenants.items()}
            overflowed = self._overflowed
            cap = self._max
        return {
            "basis": ("devtime"
                      if any(r is not None for r in rates.values())
                      else "tokens"),
            "phase_rates_s_per_token": {
                k: (round(v, 9) if v is not None else None)
                for k, v in rates.items()},
            "max_tenants": cap,
            "n_tenants": len(tenants),
            "overflowed": overflowed,
            "tenants": tenants,
        }


def merge_rollups(rollups: Iterable[Mapping[str, Mapping[str, float]]]
                  ) -> Dict[str, Dict[str, float]]:
    """Fleet-sum per-worker ``usage_by_tenant`` rollups (the router's
    ``/debug/fleet`` aggregation): same-tenant vectors add field-wise, so
    a disaggregated chat's prefill-worker and decode-replica legs land in
    ONE row."""
    out: Dict[str, Dict[str, float]] = {}
    for rollup in rollups:
        if not isinstance(rollup, Mapping):
            continue
        for tenant, vec in rollup.items():
            if not isinstance(vec, Mapping):
                continue
            agg = out.setdefault(str(tenant), {})
            for field, value in vec.items():
                try:
                    agg[field] = round(agg.get(field, 0) + float(value), 4)
                except (TypeError, ValueError):
                    continue
    return out


def _phase_rates() -> Dict[str, Optional[float]]:
    """DEVTIME's prefill/decode seconds-per-token rates (lazy import —
    usage is imported by core-adjacent modules and must not pull the
    ledger's jax dependency at import time)."""
    from generativeaiexamples_tpu.observability.devtime import DEVTIME
    return DEVTIME.phase_rates()


def worker_perf_card() -> Dict[str, Any]:
    """Compact chip-utilization card for the engine ``/health`` body —
    the per-worker numbers the router's ``/debug/fleet`` view wants that
    the load surface (running/prefilling/waiting/batch) doesn't carry:
    MFU (max over weight-bearing programs), HBM read util, padding
    waste, and mid-serving recompiles.

    MFU/HBM come from the devtime ledger's trailing-window gauges, which
    HOLD their last value while the engine idles (no decay).  The max
    runs only over programs with a timed commit in the last 60 s
    (``DEVTIME.fresh_programs``) — a one-off prefill burst's 0.5 must
    not read as the current MFU of a decode-only steady state — and
    ``measured_age_s`` carries the overall staleness for the consumer:
    a fully idle worker reports ``mfu: null`` with an old age."""
    from generativeaiexamples_tpu.observability.devtime import DEVTIME
    fresh = DEVTIME.fresh_programs(max_age_s=60.0)
    mfu_series = [value for lk, value in REGISTRY.family("engine_mfu").items()
                  if dict(lk).get("program") in fresh]
    age = DEVTIME.last_timed_age_s()
    return {
        "mfu": round(max(mfu_series), 4) if mfu_series else None,
        "hbm_read_util": round(
            REGISTRY.gauge("engine_hbm_read_util").value, 4),
        "measured_age_s": round(age, 3) if age is not None else None,
        "padding_waste_frac": round(DEVTIME.padding_waste(), 4),
        "recompiles": int(
            REGISTRY.counter("engine_recompiles_total").value),
    }


USAGE = UsageLedger()
