"""TPU LLM serving engine — the in-tree replacement for the reference's
"NIM for LLMs" container (TensorRT-LLM/vLLM continuous batching behind an
OpenAI-compatible /v1 API; ref: RAG/examples/local_deploy/
docker-compose-nim-ms.yaml:2-28, docs/architecture.md:49-61).

Architecture (JetStream-style, XLA-static):
  * `engine.py`   — jitted prefill / insert / decode-step programs over a
                    fixed-capacity slot batch (static shapes, bucketed prompts)
  * `scheduler.py`— continuous-batching orchestrator: request queue → prefill
                    → slot insertion → decode loop → per-request token streams
  * `tokenizer.py`— byte-level fallback + HF `tokenizers` wrapper + Llama-3
                    chat formatting
  * `server.py`   — aiohttp OpenAI-compatible /v1 endpoints with SSE streaming
"""

from generativeaiexamples_tpu.engine.engine import EngineCore, DecodeState  # noqa: F401
from generativeaiexamples_tpu.engine.scheduler import Scheduler, Request  # noqa: F401
from generativeaiexamples_tpu.engine.tokenizer import ByteTokenizer, get_tokenizer  # noqa: F401
